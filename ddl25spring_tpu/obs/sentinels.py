"""In-step numerics sentinels: on-device health checks for train steps.

A diverging run today fails silently (NaN loss propagates until the
checkpoint is garbage) or late (the human notices the loss curve).  The
sentinel wrapper computes the health facts *inside* the compiled step —
loss, global gradient norm, per-leaf non-finite flags, update-to-param
ratio — and surfaces them through the same ``jax.debug.callback`` host
path the :mod:`~ddl25spring_tpu.obs.counters` already use, feeding the
:mod:`~ddl25spring_tpu.obs.recorder` flight ring buffer so the last N
steps are always reconstructible from artifacts.

Gating follows the PR-1 contract exactly: every insertion decision is
made at TRACE time from one module flag (``DDL25_SENTINELS=1`` /
:func:`enable` / :func:`scoped`, read through the sanctioned
``utils.config.env_flag`` boundary), so with sentinels disabled an
instrumented step builder lowers to HLO **byte-identical** to an
uninstrumented one (pinned per strategy in ``tests/test_health.py``).
Enabled, the cost is one fused host transfer of a handful of scalars
per step (per device shard when the guard sits inside ``shard_map``).

Violation policy (``DDL25_SENTINEL_POLICY`` = ``log`` | ``halt`` |
``skip``, or per-builder override):

- ``log``: record the violation in the flight ring + counters and warn.
- ``halt``: raise :class:`SentinelViolation` from the host callback,
  carrying the offending step's flight-record context — strategy, step
  index, which metric tripped, which gradient leaves went non-finite —
  and the path of the ``flight.json`` dumped before raising.  Halt is
  TERMINAL: a callback that raises leaves the backend's dispatch
  stream errored (observed on the CPU runtime: every later dispatch in
  the process inherits the failure), which is exactly right for a run
  dying loudly but means halt is not a catch-and-continue mechanism —
  recoverable behavior is what ``skip`` is for.
- ``skip``: additionally *suppress the update on device*: the step
  returns its (params, opt_state) inputs unchanged for the poisoned
  step (a ``jnp.where`` select on the all-finite predicate), so one bad
  batch costs one step instead of the run.  (The select keeps the
  pre-step buffers live past the update, so XLA may decline the
  builders' input-output donation for that build — the expected price
  of a guarded update path.)

**Async-dispatch caveat (halt policy):** JAX dispatches steps
asynchronously, so the host callback that raises runs while the *next*
step may already be enqueued.  The exception therefore surfaces at the
next blocking point (``block_until_ready``, the next host transfer, or
``jax.effects_barrier()``) — up to one step after the poisoned one
executed on device, and possibly wrapped in the runtime's
``XlaRuntimeError``.  The flight record is written *before* the raise
and always names the exact offending step; trust the dump, not the
traceback's timing.  ``skip`` has no such lag: the select happens on
device, in the poisoned step itself.
"""

from __future__ import annotations

import contextlib
import logging
import math
import threading

from ddl25spring_tpu.utils.config import env_choice, env_flag

log = logging.getLogger(__name__)

POLICIES = ("log", "halt", "skip")

_enabled: bool = env_flag("DDL25_SENTINELS")
_policy: str = env_choice("DDL25_SENTINEL_POLICY", POLICIES, "log")
_lock = threading.Lock()
_steps: dict[str, int] = {}  # host-side per-strategy step counter
_last_violation: dict | None = None
_violation_total: int = 0  # cumulative; the ft/ autosave gate polls it


class SentinelViolation(FloatingPointError):
    """A numerics sentinel tripped under the ``halt`` policy.

    Subclasses ``FloatingPointError`` so generic float-error handling
    still catches it, but the message (and ``.context``) carry the
    flight-record context a bare FloatingPointError loses: strategy,
    step index, the violating metric, the non-finite gradient leaves,
    and the flight-dump path.
    """

    def __init__(self, message: str, context: dict | None = None):
        super().__init__(message)
        self.context = dict(context or {})


def enabled() -> bool:
    """Are sentinels on?  Checked at TRACE time by :func:`guard`."""
    return _enabled


def enable(on: bool = True) -> None:
    """Flip the sentinel flag (affects subsequent traces only, exactly
    like :func:`ddl25spring_tpu.obs.state.enable`)."""
    global _enabled
    _enabled = bool(on)


def policy() -> str:
    return _policy


def set_policy(mode: str) -> None:
    global _policy
    if mode not in POLICIES:
        raise ValueError(f"policy {mode!r} is not one of {POLICIES}")
    _policy = mode


@contextlib.contextmanager
def scoped(on: bool = True, policy: str | None = None):
    """Temporarily set the sentinel flag (and optionally the policy) —
    the test-harness entry, mirroring ``obs.scoped``."""
    global _enabled, _policy
    prev, prev_pol = _enabled, _policy
    _enabled = bool(on)
    if policy is not None:
        set_policy(policy)
    try:
        yield
    finally:
        _enabled, _policy = prev, prev_pol


def resolve(
    enabled: bool | None = None, policy: str | None = None
) -> tuple[bool, str]:
    """BUILD-time resolution of the sentinel gate + policy, mirroring
    the ``instr = obs.enabled() if instrument is None else ...``
    convention of PR 1.  Builders call this when the step is *built* and
    bake the result into the traced closure — tracing happens lazily (at
    ``.lower()`` or first call), possibly long after a ``scoped()``
    block or an ``enable()`` toggle has been unwound, so reading module
    state at trace time would silently follow the wrong flag."""
    on = _enabled if enabled is None else bool(enabled)
    mode = _policy if policy is None else policy
    if mode not in POLICIES:
        raise ValueError(f"policy {mode!r} is not one of {POLICIES}")
    return on, mode


def last_violation() -> dict | None:
    """The most recent violation record (host side), or None."""
    with _lock:
        return dict(_last_violation) if _last_violation else None


def violation_count() -> int:
    """Cumulative violations observed in this process (all strategies).
    The poisoned-checkpoint gate (:mod:`ddl25spring_tpu.ft.autosave`)
    compares this across save attempts: a step flagged non-finite since
    the last save means the pending state must not be persisted."""
    with _lock:
        return _violation_total


def reset() -> None:
    """Clear host-side step counters + last violation (test harness)."""
    global _last_violation, _violation_total
    with _lock:
        _steps.clear()
        _last_violation = None
        _violation_total = 0


# --------------------------------------------------------------- the guard


def guard(
    strategy: str,
    results,
    *,
    loss=None,
    grads=None,
    params=None,
    updates=None,
    fallback=None,
    axis=None,
    enabled: bool | None = None,
    policy: str | None = None,
):
    """The generic sentinel wrapper every train-step builder opts into.

    Call INSIDE the jitted step, after the update — with the gate and
    policy resolved at BUILD time (see :func:`resolve`; passing the raw
    tri-state kwarg here would read the module flag lazily at trace
    time, after any ``scoped()`` block has unwound)::

        s_on, s_policy = sentinels.resolve(sentinel)  # at build time
        ...
        new_params = optax.apply_updates(params, updates)
        new_params, opt_state = sentinels.guard(
            "dp", (new_params, opt_state), loss=loss, grads=grads,
            params=params, updates=updates,
            fallback=(params, opt_state_in),
            enabled=s_on, policy=s_policy)

    ``results`` is the pytree the step is about to return (minus the
    loss, which policies never rewrite); ``fallback`` is the matching
    pre-update pytree the ``skip`` policy selects when the step is
    poisoned.  ``axis``: when the guard sits inside ``shard_map``, the
    mesh axis to reduce over so norms/flags are global (the callback
    then fires per shard; the host side keeps shard 0's record).

    ``enabled`` is the per-builder tri-state (None = follow the module
    flag at trace time; True/False hard-enable/-disable), ``policy``
    the per-builder override of the module policy.  Disabled, this
    returns ``results`` **unchanged** — the same object, nothing enters
    the HLO (the zero-cost contract, pinned in ``tests/test_health.py``).
    """
    on = _enabled if enabled is None else bool(enabled)
    if not on:
        return results
    import jax
    import jax.numpy as jnp
    from jax import lax

    mode = _policy if policy is None else policy
    if mode not in POLICIES:
        raise ValueError(f"policy {mode!r} is not one of {POLICIES}")

    # per-leaf flags cover grads AND updates: an optimizer whose state
    # went non-finite poisons the update while the grads are still
    # clean (e.g. NaN Adam moments) — checking grads alone would detect
    # it one step late, after skip's fallback is already poisoned
    leaves, leaf_names = [], []
    for prefix, tree in (("grads", grads), ("updates", updates)):
        if tree is None:
            continue
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        leaf_names += [prefix + jax.tree_util.keystr(p) for p, _ in flat]
        leaves += [l for _, l in flat]

    def _sumsq(tree):
        if tree is None:
            return None
        return sum(
            jnp.sum(jnp.square(l.astype(jnp.float32)))
            for l in jax.tree.leaves(tree)
        )

    gnorm2 = _sumsq(grads)
    unorm2 = _sumsq(updates)
    pnorm2 = _sumsq(params)
    if leaves:
        flags = jnp.stack(
            [jnp.any(~jnp.isfinite(l)).astype(jnp.float32) for l in leaves]
        )
    else:
        flags = jnp.zeros((0,), jnp.float32)
    loss_val = (
        jnp.asarray(loss, jnp.float32)
        if loss is not None else jnp.float32(0.0)
    )
    shard_idx = jnp.int32(0)
    if axis is not None:
        # inside shard_map: make every reduced fact global before it
        # crosses to the host (each shard holds distinct rows of the
        # grad layout, so psum of square-norms IS the global norm²)
        gnorm2 = lax.psum(gnorm2, axis) if gnorm2 is not None else None
        unorm2 = lax.psum(unorm2, axis) if unorm2 is not None else None
        pnorm2 = lax.psum(pnorm2, axis) if pnorm2 is not None else None
        if leaves:
            flags = lax.pmax(flags, axis)
        shard_idx = lax.axis_index(axis)

    neg1 = jnp.float32(-1.0)  # "not measured" marker (host side reads <0)
    gnorm2_c = gnorm2 if gnorm2 is not None else neg1
    unorm2_c = unorm2 if unorm2 is not None else neg1
    pnorm2_c = pnorm2 if pnorm2 is not None else neg1

    ok = jnp.isfinite(loss_val)
    if gnorm2 is not None:
        ok = ok & jnp.isfinite(gnorm2)
    if unorm2 is not None:
        ok = ok & jnp.isfinite(unorm2)
    if leaves:
        ok = ok & (jnp.sum(flags) == 0)

    # static context rides a partial, NOT callback kwargs (the callback
    # protocol treats kwargs as traced pytrees; strings aren't jax types)
    from functools import partial as _partial

    jax.debug.callback(
        _partial(
            _on_step,
            strategy=strategy, leaf_names=tuple(leaf_names), mode=mode,
            has_loss=loss is not None,
        ),
        loss_val, gnorm2_c, flags, unorm2_c, pnorm2_c, ok, shard_idx,
    )

    if mode == "skip" and fallback is not None:
        results = jax.tree.map(
            lambda new, old: jnp.where(ok, new, old), results, fallback
        )
    return results


def _on_step(
    loss, gnorm2, flags, unorm2, pnorm2, ok, shard_idx,
    *, strategy, leaf_names, mode, has_loss,
):
    """Host side of the sentinel callback: fold the step's facts into
    the flight ring + counters; enforce the policy on violation."""
    from ddl25spring_tpu.obs.counters import counters as _counters
    from ddl25spring_tpu.obs.recorder import flight

    if int(shard_idx) != 0:
        # shard_map replays the callback once per shard with identical
        # (already globally reduced) values: keep shard 0's record, but
        # let every arrival count as liveness for the stall watchdog
        flight.beat()
        return

    global _last_violation, _violation_total
    loss = float(loss)
    gnorm = math.sqrt(g2) if (g2 := float(gnorm2)) >= 0 else None
    u2, p2 = float(unorm2), float(pnorm2)
    ratio = (
        math.sqrt(u2) / (math.sqrt(p2) + 1e-20)
        if u2 >= 0 and p2 >= 0 else None
    )
    bad_leaves = [n for n, f in zip(leaf_names, flags) if float(f) > 0]
    violation = not bool(ok)

    with _lock:
        step = _steps.get(strategy, 0)
        _steps[strategy] = step + 1

    if has_loss:
        _counters.add(f"{strategy}.sentinel.loss", loss)
    if gnorm is not None and math.isfinite(gnorm):
        _counters.add(f"{strategy}.sentinel.grad_norm", gnorm)
    if ratio is not None and math.isfinite(ratio):
        _counters.add(f"{strategy}.sentinel.update_ratio", ratio)

    rec = {
        "strategy": strategy,
        "step": step,
        "policy": mode,
        "violation": violation,
        **({"loss": loss} if has_loss else {}),
        **({"grad_norm": gnorm} if gnorm is not None else {}),
        **({"update_ratio": ratio} if ratio is not None else {}),
        **({"nonfinite_leaves": bad_leaves} if bad_leaves else {}),
    }
    if not violation:
        flight.record(kind="step", **rec)
        return

    # name the single most specific metric that tripped — the halt
    # message and the dump must identify it without post-processing
    # (leaf names arrive prefixed "grads..."/"updates...")
    if bad_leaves:
        metric = bad_leaves[0]
    elif has_loss and not math.isfinite(loss):
        metric = "loss"
    else:
        metric = "grad_norm"
    rec["violating_metric"] = metric
    flight.record(kind="violation", **rec)
    _counters.add("sentinel.violations", 1.0)
    with _lock:
        _last_violation = dict(rec)
        _violation_total += 1

    msg = (
        f"sentinel violation in strategy={strategy!r} step={step}: "
        f"{metric} went non-finite"
        + (f" (loss={loss})" if has_loss else "")
        + (f"; non-finite leaves: {bad_leaves}" if bad_leaves else "")
    )
    if mode == "halt":
        path = None
        try:
            path = flight.dump(reason="sentinel_halt")
        except Exception as e:  # noqa: BLE001 — the dump must not
            # mask the violation itself
            log.warning("flight dump failed during halt: %s", e)
        raise SentinelViolation(
            msg + (f"; flight record dumped to {path}" if path else ""),
            context=dict(rec, flight_dump=path),
        )
    if mode == "skip":
        log.warning("%s; policy=skip — update suppressed on device", msg)
    else:
        log.warning("%s; policy=log — continuing", msg)
