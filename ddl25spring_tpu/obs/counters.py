"""On-device counters surfaced through ``jax.debug.callback``.

The XLA profiler is unusable on tunneled TPU transports (RESULTS §6a), so
values that live *inside* jitted step functions — MoE router load-balance
stats, per-tick pipeline progress, ZeRO collective volumes — are surfaced
by a host callback instead: ``emit()`` inserts a ``jax.debug.callback``
whose host side folds the value into a named accumulator, and ``mark()``
records (index, host arrival time) pairs so tick cadence can be estimated
without any device tracing.

Zero cost when disabled: every inserter checks :func:`state.enabled` at
TRACE time and inserts nothing when telemetry is off — the lowered HLO is
byte-identical to the uninstrumented program (``tests/test_obs.py``).
When enabled, the cost is one small host transfer per emit per device
shard (callbacks fire once per shard under ``shard_map``; the accumulator
sees every shard's value, which is exactly what load-balance stats want).

Static facts that are known at trace time and carry no runtime cost even
when enabled — e.g. bytes moved by ZeRO's all_gather per step — go through
:func:`add_static`.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any

from ddl25spring_tpu.obs import state


class CounterSet:
    """Named host-side accumulators fed from inside (or outside) jit."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._scalars: dict[str, dict[str, float]] = {}
        self._series: dict[str, list[tuple[float, float]]] = {}
        self._static: dict[str, Any] = {}
        self._t0 = time.perf_counter()

    # ---- host-side ------------------------------------------------------
    def add(self, name: str, value: float) -> None:
        """Fold ``value`` into the named scalar accumulator (host call)."""
        v = float(value)
        if math.isnan(v):
            return
        with self._lock:
            s = self._scalars.setdefault(
                name,
                {"sum": 0.0, "count": 0.0, "min": math.inf, "max": -math.inf},
            )
            s["sum"] += v
            s["count"] += 1
            s["last"] = v
            s["min"] = min(s["min"], v)
            s["max"] = max(s["max"], v)

    def observe(self, name: str, index: float) -> None:
        """Append ``(index, host wall time)`` to the named series."""
        t = time.perf_counter() - self._t0
        with self._lock:
            self._series.setdefault(name, []).append((float(index), t))

    def add_static(self, name: str, value: Any) -> None:
        """Record a trace-time fact (idempotent per name: last write wins —
        rebuilding a step function re-records the same value)."""
        with self._lock:
            self._static[name] = value

    # ---- inside-jit inserters ------------------------------------------
    def emit(self, name: str, value, force: bool = False) -> None:
        """Accumulate a traced scalar into ``name`` on the host.

        Call from INSIDE a jitted function.  Trace-time no-op when
        telemetry is disabled (nothing enters the HLO) unless ``force`` —
        the builders pass it so an explicit ``instrument=True`` (or a
        build-time-enabled flag) wins over the global flag's state at
        trace time.
        """
        if not (force or state.enabled()):
            return
        import jax

        jax.debug.callback(lambda v, _n=name: self.add(_n, v), value)

    def mark(self, name: str, index, force: bool = False) -> None:
        """Record the host arrival time of a traced marker (e.g. the tick
        counter of a pipeline scan) into the named series.  Trace-time
        no-op when disabled unless ``force`` (see :meth:`emit`)."""
        if not (force or state.enabled()):
            return
        import jax

        jax.debug.callback(lambda i, _n=name: self.observe(_n, i), index)

    # ---- export ---------------------------------------------------------
    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            scalars = {
                n: dict(
                    s,
                    mean=(s["sum"] / s["count"]) if s["count"] else None,
                )
                for n, s in self._scalars.items()
            }
            return {
                "scalars": scalars,
                "series": {n: list(v) for n, v in self._series.items()},
                "static": dict(self._static),
            }

    def save(self, run_dir: str, filename: str = "counters.json") -> str:
        os.makedirs(run_dir, exist_ok=True)
        path = os.path.join(run_dir, filename)
        with open(path, "w") as f:
            json.dump(self.snapshot(), f, indent=1)
        return path

    def reset(self) -> None:
        with self._lock:
            self._scalars.clear()
            self._series.clear()
            self._static.clear()
            self._t0 = time.perf_counter()


counters = CounterSet()


def gpipe_bubble_fraction(num_stages: int, num_microbatches: int) -> float:
    """The GPipe schedule's idle fraction ``(S-1)/(M+S-1)`` (the schedule
    runs ``M+S-1`` ticks of which ``S-1`` are fill/drain per stage) —
    the analytic anchor the measured tick cadence is compared against."""
    s, m = int(num_stages), int(num_microbatches)
    if s <= 1:
        return 0.0
    return (s - 1) / (m + s - 1)
