"""Steady-state perf measurement: measured MFU, collective wall-clock
attribution, and the cross-run regression ledger.

The compile-time stack (PR 2: :mod:`ddl25spring_tpu.obs.xla_analytics`)
can only **project** performance — roofline MFU from compiled
FLOPs/bytes — and the run telemetry (PR 1) only **times** it coarsely
(p50 steps/sec).  Neither says where a step's wall clock actually goes,
so a perf PR "fixing what the linter found" (the sync grad all-reduces
graft-lint H001 flags) has no measured before/after.  This module is
that instrument.  For any registered ``describe()`` strategy (and the
bench workloads via :func:`measure_bench_step`) it produces a
**measured perf record**:

(a) *step wall time* — warmed, ``block_until_ready``-barriered p50/p95
    over K reps of the compiled step (the steady-state loop rebinds
    params/opt-state through the step's own outputs, so buffer donation
    behaves exactly as in training);
(b) *compute-only counterfactual* — the same strategy lowered on a
    ONE-device mesh (every collective degenerates to a copy/no-op in
    the optimized HLO) and timed the same way: the step's compute time
    without any cross-device traffic;
(c) *per-collective micro-costing* — every entry in the compile-time
    collective inventory re-synthesized standalone (same kind, payload
    bytes, dtype, mesh axes, participant count — a one-op ``shard_map``
    program on the same mesh) and timed: a measured comms cost model.

From these: **exposed-comms time** (step − compute: the traffic the
schedule failed to hide), **achieved overlap efficiency**
(1 − exposed/Σmicro, capped at 1.0 and floor-free — 1.0 means every
measured comms second hid behind compute; negative means the exposed
gap exceeds even the un-overlapped comms bill, i.e. non-comms overhead
such as fake-mesh core contention is leaking into it), and **measured
MFU** (compiled FLOPs / (wall × chip peak × chips)) with the
**projection error** against the PR-2 roofline.  On
the CPU CI image the peak is the runtime-calibrated ``cpu-host``
pseudo-spec (:func:`ddl25spring_tpu.utils.flops.
calibrated_host_peak_flops`), so every number is defined — as a
host-relative trend signal, which is exactly what the regression
ledger needs.

Records append to ``runs/perf_ledger.jsonl`` keyed by (strategy, mesh,
host fingerprint, git sha); ``tools/perf_report.py`` renders per-key
trend tables and ``--check`` gates regressions against tolerance bands
(the CI ``perf-smoke`` job).  H001 findings riding the strategy's
compile report are cross-referenced with the measured micro-cost of the
very op they flag (:func:`ddl25spring_tpu.analysis.engine.
attach_measured_costs`), so "overlap left on the table" carries a
millisecond figure.

CLI (CPU-only, fake multi-device host)::

    python -m ddl25spring_tpu.obs.perfscope --strategy dp,zero3-prefetch
    python -m ddl25spring_tpu.obs.perfscope --strategy dp --rounds 2

Caveats: on fake CPU devices every "chip" shares the host's cores, so
absolute numbers are host-relative — compare trends on ONE host (the
ledger key includes the fingerprint), never across machines.  Timing
noise is real at microsecond scales; the report tool's tolerance bands
exist for exactly that.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any

DEFAULT_LEDGER = os.path.join("runs", "perf_ledger.jsonl")
PERF_BASENAME = "perf.json"

# the kinds the micro-cost synthesizer can rebuild standalone; a kind
# outside this set (collective-broadcast) records cost None with a note
_SYNTH_KINDS = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)

_HLO_TO_NP = {
    "pred": "bool", "bf16": "bfloat16", "f16": "float16", "f32": "float32",
    "f64": "float64", "s8": "int8", "s16": "int16", "s32": "int32",
    "s64": "int64", "u8": "uint8", "u16": "uint16", "u32": "uint32",
    "u64": "uint64",
}


def host_fingerprint() -> str:
    """Stable-ish identity of the measuring machine+backend — part of
    the ledger key, so one host's trend never gates another's."""
    import platform as _platform

    import jax

    try:
        d = jax.devices()[0]
        kind = getattr(d, "device_kind", None) or d.platform
    except Exception:  # noqa: BLE001 — no backend, still fingerprintable
        kind = "no-backend"
    return f"{_platform.node()}/{os.cpu_count()}cpu/{kind}"


def _pct(xs: list[float], q: float) -> float:
    import numpy as np

    return float(np.percentile(np.asarray(xs, dtype=np.float64), q))


# ------------------------------------------------------------ step timing


def measure_step(
    fn: Any,
    args: tuple,
    *,
    reps: int = 10,
    warmup: int = 3,
    rebind: bool = False,
    return_args: bool = False,
):
    """Warmed, barriered wall times of ``reps`` calls of ``fn(*args)``.

    ``rebind=True`` treats ``fn`` as a train step whose first two
    outputs replace ``args[0:2]`` each call — the steady-state training
    loop, and the only calling convention that survives buffer donation
    (a donated input is DEAD after the call; re-feeding it would raise).
    Each rep is individually ``jax.block_until_ready``-barriered, so a
    wall time covers exactly one dispatch's device work.  Returns the
    stats dict (``{"reps", "warmup", "step_s_p50", "step_s_p95",
    "step_s_min", "times_s"}``); with ``return_args=True``, ``(stats,
    final_args)`` so callers can keep using the live buffers."""
    import jax

    a = tuple(args)

    def call(a):
        out = fn(*a)
        jax.block_until_ready(out)
        if rebind:
            a = (out[0], out[1]) + a[2:]
        return a

    for _ in range(max(warmup, 1)):  # >= 1: the first call compiles
        a = call(a)
    times: list[float] = []
    for _ in range(max(reps, 1)):
        t0 = time.perf_counter()
        a = call(a)
        times.append(time.perf_counter() - t0)
    stats = {
        "reps": len(times),
        "warmup": warmup,
        "step_s_p50": _pct(times, 50),
        "step_s_p95": _pct(times, 95),
        "step_s_min": min(times),
        "times_s": [round(t, 6) for t in times],
    }
    return (stats, a) if return_args else stats


# ------------------------------------------------- collective micro-costs


def _synth_collective(mesh, kind, nbytes, dtype, axes, group_size):
    """Build ``(jitted_fn, input_array)`` reproducing one inventory
    entry standalone: a one-op shard_map program on ``mesh`` moving the
    same payload bytes/dtype over the same axes with the same
    participant count.  Raises when the kind/axes combination cannot be
    re-synthesized (caller records the site as uncosted)."""
    import jax
    import numpy as np
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ddl25spring_tpu.utils.compat import pcast, shard_map

    n = int(group_size)
    np_dtype = np.dtype(_HLO_TO_NP.get(dtype or "f32", "float32"))
    elems = max(int(nbytes) // np_dtype.itemsize, n)
    elems = -(-elems // n) * n  # divisible by the participant count
    ax = tuple(axes) if len(axes) > 1 else axes[0]
    spec_sharded = P(tuple(axes))

    # the replicated-input bodies (all-reduce / reduce-scatter) pcast
    # their operand varying first: VMA-typed shard_map rejects a psum
    # of an unvarying value (identity shim on pre-VMA jax)
    if kind == "all-reduce":
        # per-device payload == result bytes; replicated in and out
        def body(v):
            return lax.psum(pcast(v, ax, to="varying"), ax)

        in_spec, out_spec, global_shape = P(), P(), (elems,)
    elif kind == "all-gather":
        # result bytes is the GATHERED buffer; each device holds 1/n
        def body(v):
            return lax.all_gather(v, ax, tiled=True)

        in_spec, out_spec, global_shape = spec_sharded, P(), (elems,)
    elif kind == "reduce-scatter":
        # result bytes is the per-device SHARD; input is n shards
        def body(v):
            return lax.psum_scatter(
                pcast(v, ax, to="varying"), ax, tiled=True
            )

        in_spec, out_spec, global_shape = P(), spec_sharded, (elems * n,)
    elif kind == "collective-permute":
        if len(axes) != 1:
            raise ValueError(f"permute over {len(axes)} axes unsupported")

        def body(v):
            return lax.ppermute(
                v, ax, perm=[(i, (i + 1) % n) for i in range(n)]
            )

        in_spec, out_spec, global_shape = (
            spec_sharded, spec_sharded, (elems * n,),
        )
    elif kind == "all-to-all":
        if len(axes) != 1:
            raise ValueError(f"all-to-all over {len(axes)} axes unsupported")

        def body(v):
            return lax.all_to_all(
                v.reshape(n, -1), ax, 0, 0, tiled=True
            ).reshape(-1)

        in_spec, out_spec, global_shape = (
            spec_sharded, spec_sharded, (elems * n,),
        )
    else:
        raise ValueError(f"cannot synthesize collective kind {kind!r}")

    fn = jax.jit(
        shard_map(body, mesh=mesh, in_specs=in_spec, out_specs=out_spec)
    )
    x = jax.device_put(
        np.zeros(global_shape, np_dtype), NamedSharding(mesh, in_spec)
    )
    return fn, x


def build_micro_benches(mesh, ops: list[dict[str, Any]]):
    """Compile one standalone micro-bench per UNIQUE (kind, bytes,
    dtype, axes, group) signature in the op inventory.  Returns
    ``(benches, site_keys)``: ``benches[key] = (fn, x)`` or an error
    string; ``site_keys[i]`` maps ``ops[i]`` to its key (None when the
    site has no cross-device communication on this mesh)."""
    benches: dict[tuple, Any] = {}
    site_keys: list[tuple | None] = []
    for op in ops:
        axes = tuple(op.get("axes") or ())
        group = op.get("group_size") or 0
        if not axes or group < 2 or op["kind"] not in _SYNTH_KINDS:
            site_keys.append(None)
            continue
        key = (op["kind"], op["result_bytes"], op.get("dtype"), axes, group)
        site_keys.append(key)
        if key in benches:
            continue
        try:
            benches[key] = _synth_collective(
                mesh, op["kind"], op["result_bytes"], op.get("dtype"),
                axes, group,
            )
        except Exception as e:  # noqa: BLE001 — one odd op, not the record
            benches[key] = f"{type(e).__name__}: {e}"
    return benches, site_keys


def time_micro_benches(
    benches: dict[tuple, Any], *, reps: int = 5, warmup: int = 2,
    inner: int = 4,
) -> dict[tuple, Any]:
    """Per-execution seconds for each compiled micro-bench (``inner``
    back-to-back launches per timed window amortize the per-dispatch
    host overhead that would otherwise swamp a microsecond-scale
    collective).

    The estimator is the MIN over the timed windows, not a percentile:
    the micro table is a *cost model* — what this collective
    intrinsically costs standalone on this mesh — and the least-
    contended window is the best estimate of that.  A p50 inherits
    whatever ambient load the measuring process carries at that moment
    (measured on the bench path: up to 4x inflation right after the
    timed phases' memory pressure), which then poisons every
    ``overlap_eff`` that divides by the micro total."""
    import jax

    out: dict[tuple, Any] = {}
    for key, bench in benches.items():
        if isinstance(bench, str):
            out[key] = bench
            continue
        fn, x = bench
        try:
            for _ in range(max(warmup, 1)):
                jax.block_until_ready(fn(x))
            walls = []
            for _ in range(max(reps, 1)):
                t0 = time.perf_counter()
                for _ in range(inner):
                    jax.block_until_ready(fn(x))
                walls.append((time.perf_counter() - t0) / inner)
            out[key] = min(walls)
        except Exception as e:  # noqa: BLE001 — degrade per bench
            out[key] = f"{type(e).__name__}: {e}"
    return out


def micro_site_records(
    ops: list[dict[str, Any]],
    site_keys: list[tuple | None],
    costs: dict[tuple, Any],
) -> list[dict[str, Any]]:
    """One measured-cost record per inventory op SITE — the inventory
    coverage is exact by construction (every site appears, costed or
    not), which the decomposition tests pin."""
    sites = []
    for op, key in zip(ops, site_keys):
        rec: dict[str, Any] = {
            "op": op.get("name"),
            "kind": op["kind"],
            "result_bytes": op["result_bytes"],
            "dtype": op.get("dtype"),
            "axes": op.get("axes"),
            "group_size": op.get("group_size"),
            "count": op["count"],
        }
        cost = costs.get(key) if key is not None else None
        if isinstance(cost, float):
            rec["t_s"] = cost
            rec["t_total_s"] = cost * op["count"]
        else:
            rec["t_s"] = None
            rec["note"] = (
                cost if isinstance(cost, str)
                else "no cross-device communication on this mesh"
            )
        sites.append(rec)
    return sites


# --------------------------------------------------------- record building


def build_record(
    *,
    strategy: str,
    mesh_axes: dict[str, int] | None,
    n_chips: int,
    step: dict[str, Any],
    compute: dict[str, Any] | None = None,
    compute_error: str | None = None,
    micro: list[dict[str, Any]] | None = None,
    flops: float | None = None,
    bytes_accessed: float | None = None,
    wire_bytes: float | None = None,
    device: Any = None,
    extra: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """Assemble one ledger record from the three measurements.

    Derivations (every one None-safe — a missing ingredient nulls the
    derived field, never fakes it):

    - ``exposed_comms_s = max(0, step_p50 - compute_p50)`` — the comms
      time the schedule failed to hide behind compute;
    - ``overlap_eff = 1 - exposed / micro_total`` capped at 1.0, floor-
      free (None when the program has no costed collectives): 1.0 means
      every measured comms second hid behind compute, 0 means exactly
      the standalone comms bill stayed exposed, and NEGATIVE values
      mean the exposed gap exceeds even the un-overlapped comms bill —
      non-comms overhead is leaking into the gap (on fake CPU meshes,
      the n device programs contending for this host's cores).  A [0, 1]
      floor would erase exactly that signal: a step whose exposure
      doubles from 10x to 20x the comms bill would read 0.0 -> 0.0,
      invisible to the ``--min-overlap-eff`` gate and to before/after
      comparisons on contended hosts — so the floor is the reader's
      job, not the record's;
    - ``measured_mfu = flops / (step_p50 * n_chips * peak)`` with the
      chip peak from :func:`~ddl25spring_tpu.utils.flops.
      host_peak_spec` (datasheet on TPU, calibrated on cpu-host);
    - ``projection_err = measured_mfu / projected_mfu - 1`` against the
      PR-2 roofline evaluated on the SAME chip spec.
    """
    import jax

    from ddl25spring_tpu.obs.logger import git_sha
    from ddl25spring_tpu.obs.xla_analytics import roofline_projection
    from ddl25spring_tpu.utils.flops import CPU_HOST_KIND, host_peak_spec

    step_s = step["step_s_p50"]
    compute_s = compute["step_s_p50"] if compute else None
    exposed = (
        max(0.0, step_s - compute_s) if compute_s is not None else None
    )
    micro = micro or []
    costed = [m["t_total_s"] for m in micro if m.get("t_s") is not None]
    micro_total = sum(costed) if costed else 0.0
    overlap_eff = None
    if exposed is not None and micro_total > 0:
        overlap_eff = min(1.0, 1.0 - exposed / micro_total)

    kind, spec = host_peak_spec(device)
    peak = (spec or {}).get("peak_bf16_flops")
    measured_mfu = None
    if flops and peak and step_s > 0:
        measured_mfu = flops / (step_s * max(n_chips, 1) * peak)
    projected_mfu = projected_bound = None
    if flops and spec and kind:
        proj = roofline_projection(
            flops, bytes_accessed, float(wire_bytes or 0.0),
            chips=[kind], specs={kind: spec},
        ).get(kind)
        if proj:
            projected_mfu = proj["projected_mfu"]
            projected_bound = proj["bound"]
    projection_err = None
    if measured_mfu is not None and projected_mfu:
        projection_err = measured_mfu / projected_mfu - 1.0

    return {
        "record": "perf",
        "schema": 1,
        "ts": time.time(),
        "strategy": strategy,
        "mesh": mesh_axes,
        "n_chips": n_chips,
        "host": host_fingerprint(),
        "git_sha": git_sha(),
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "chip": kind,
        "peak_flops_per_chip": peak,
        # None when no peak exists (failed calibration / unknown chip):
        # a peak-less record nulls measured_mfu rather than faking one
        "peak_source": (
            None if peak is None
            else "calibrated-host" if kind == CPU_HOST_KIND
            else "datasheet"
        ),
        "reps": step["reps"],
        "warmup": step["warmup"],
        "step_s_p50": step_s,
        "step_s_p95": step["step_s_p95"],
        "step_s_min": step["step_s_min"],
        "compute_s_p50": compute_s,
        **({"compute_error": compute_error} if compute_error else {}),
        "exposed_comms_s": exposed,
        "micro_total_s": micro_total,
        "overlap_eff": overlap_eff,
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "wire_bytes": wire_bytes,
        "measured_mfu": measured_mfu,
        "projected_mfu": projected_mfu,
        "projected_bound": projected_bound,
        "projection_err": projection_err,
        "micro": micro,
        **(extra or {}),
    }


def perf_cell(record: dict[str, Any]) -> dict[str, Any]:
    """The compact ``telemetry.perf`` cell a BENCH line carries (ms
    where a human reads it; the full record stays in the ledger)."""

    def ms(key):
        v = record.get(key)
        return round(v * 1e3, 4) if v is not None else None

    return {
        "measured_mfu": record.get("measured_mfu"),
        "overlap_eff": record.get("overlap_eff"),
        # the analytical ceiling on overlap_eff from the schedule
        # verifier (analysis/sched.py) — noise-free where the measured
        # number is noise-bound on contended CI hosts
        "static_overlap_bound": record.get("static_overlap_bound"),
        "exposed_comms_ms": ms("exposed_comms_s"),
        "projection_err": record.get("projection_err"),
        "step_ms_p50": ms("step_s_p50"),
        "compute_ms_p50": ms("compute_s_p50"),
        "micro_total_ms": ms("micro_total_s"),
        "chip": record.get("chip"),
        "peak_source": record.get("peak_source"),
    }


def measure_callable(
    fn: Any,
    args: tuple,
    *,
    strategy: str,
    reps: int = 10,
    warmup: int = 3,
    rebind: bool = False,
    flops: float | None = None,
    n_chips: int = 1,
) -> dict[str, Any]:
    """Measure an arbitrary step (no mesh, no counterfactual, no
    micro-costing) into a ledger-shaped record — the harness for ad-hoc
    steps and the regression-gate tests."""
    stats = measure_step(fn, args, reps=reps, warmup=warmup, rebind=rebind)
    return build_record(
        strategy=strategy, mesh_axes=None, n_chips=n_chips,
        step=stats, flops=flops,
    )


# ----------------------------------------------------- strategy measurement


def measure_strategy(
    name: str,
    mesh_sizes: tuple[int, ...] | None = None,
    *,
    reps: int = 10,
    warmup: int = 3,
    micro_reps: int = 5,
    rounds: int = 1,
    compute_counterfactual: bool = True,
    **overrides: Any,
) -> list[dict[str, Any]]:
    """The full perfscope pass over one registered strategy: compile on
    its fake mesh, time the step, time the 1-device counterfactual,
    micro-cost the collective inventory, derive, and cross-reference
    H001 findings.  Returns ``rounds`` records (every round re-times
    the SAME compiled programs — how the CI job gives the regression
    gate a baseline without paying compilation twice).  ``overrides``
    forward to the strategy's ``describe()`` (how ``tools/bucket_sweep.
    py`` re-describes one strategy per ``bucket_bytes`` grid point)."""
    from ddl25spring_tpu.analysis.engine import attach_measured_costs
    from ddl25spring_tpu.obs import xla_analytics as xa

    mesh = xa.strategy_mesh(name, mesh_sizes)
    d = xa.describe_strategy(name, mesh, **overrides)
    compiled = d["fn"].lower(*d["args"]).compile()
    hlo_text = compiled.as_text()
    report = xa.analyze_compiled(
        compiled, mesh, meta=d.get("meta"), hlo_text=hlo_text
    )
    xa.attach_findings(report, compiled, strategy=name, hlo_text=hlo_text)
    rebind = d.get("lowered", "train_step") == "train_step"
    mesh_axes = {
        ax: int(s) for ax, s in zip(mesh.axis_names, mesh.devices.shape)
    }
    n_chips = math.prod(mesh_axes.values())

    # compute-only counterfactual: same strategy, every axis collapsed
    # to 1 — the optimized HLO is collective-free (trivial groups fold
    # to copies), and the per-device workload matches because describe()
    # scales its example batch with the mesh
    c1 = d1 = None
    compute_error = None
    if compute_counterfactual:
        try:
            mesh1 = xa.strategy_mesh(name, (1,) * len(mesh.axis_names))
            d1 = xa.describe_strategy(name, mesh1, **overrides)
            c1 = d1["fn"].lower(*d1["args"]).compile()
        except Exception as e:  # noqa: BLE001 — a strategy that cannot
            # shrink to one device still gets step + micro measurements
            compute_error = f"{type(e).__name__}: {e}"

    ops = report["collectives"]["ops"]
    benches, site_keys = build_micro_benches(mesh, ops)
    wire_total = sum(
        t["wire_bytes"] for t in report["collectives"]["totals"].values()
    )

    records = []
    # args thread through the rounds via the step's own outputs: a
    # donated buffer is DEAD after its call, so round 2 must feed the
    # live arrays round 1 returned, exactly like a training loop
    cur_args = d["args"]
    cur_args1 = d1["args"] if d1 is not None else None
    rebind1 = (
        d1.get("lowered", "train_step") == "train_step"
        if d1 is not None else False
    )
    for _ in range(max(rounds, 1)):
        step_stats, cur_args = measure_step(
            compiled, cur_args, reps=reps, warmup=warmup, rebind=rebind,
            return_args=True,
        )
        compute_stats = None
        if c1 is not None:
            compute_stats, cur_args1 = measure_step(
                c1, cur_args1, reps=reps, warmup=warmup, rebind=rebind1,
                return_args=True,
            )
        costs = time_micro_benches(benches, reps=micro_reps)
        micro = micro_site_records(ops, site_keys, costs)
        meta = d.get("meta") or {}
        sched = report.get("sched") or {}
        rec = build_record(
            strategy=name, mesh_axes=mesh_axes, n_chips=n_chips,
            step=step_stats, compute=compute_stats,
            compute_error=compute_error, micro=micro,
            flops=report.get("flops"),
            bytes_accessed=report.get("bytes_accessed"),
            wire_bytes=wire_total,
            # the bucket threshold / overlap mode the strategy compiled
            # with: the sweep + before/after ledger comparisons key on
            # these being explicit in every record — plus the schedule
            # verifier's analytical overlap ceiling, so every measured
            # overlap_eff ships next to its noise-free static bound
            extra={
                **{
                    k: meta[k]
                    for k in ("bucket_bytes", "n_buckets", "overlap")
                    if k in meta
                },
                "static_overlap_bound": sched.get("static_overlap_bound"),
            },
        )
        # the linter's overlap complaints (H001) gain the measured cost
        # of the very op they flag (and underwater overlap windows gain
        # H010 findings); the trimmed findings ride the record
        findings = [dict(f) for f in report.get("findings", [])]
        attach_measured_costs(findings, rec, sched=sched, strategy=name)
        rec["findings"] = [
            {k: f.get(k) for k in (
                "rule", "severity", "op", "bytes", "source", "waived",
                "measured",
            )}
            for f in findings
        ]
        records.append(rec)
    return records


# ------------------------------------------------------- bench-step wiring


def measure_bench_step(
    step: Any,
    params: Any,
    opt_state: Any,
    batch: Any,
    meta: dict[str, Any],
    devices: list,
    *,
    reps: int = 8,
    warmup: int = 2,
    micro_reps: int = 4,
    per_chip_batch: int | None = None,
):
    """Perfscope over the LIVE bench train step (``bench.py`` calls this
    after the timed phases, replacing its old lower-for-FLOPs-only
    pass — same lower+compile cost, full measurement out).

    The compute counterfactual: with one chip the measured step IS
    collective-free, so it is simply re-timed (zero extra compile);
    with more, the same ResNet config is rebuilt on a single device at
    the same per-chip batch (:func:`ddl25spring_tpu.benchmarks.
    build_compute_counterfactual`).  Returns ``(record, params,
    opt_state)`` — the step donates its buffers, so the caller must
    rebind from the returned live arrays."""
    import jax.numpy as jnp

    from ddl25spring_tpu.obs import xla_analytics as xa
    from ddl25spring_tpu.utils.compat import compiled_cost_analysis

    mesh = meta["mesh"]
    n_chips = int(meta["n_chips"])
    compiled = step.lower(params, opt_state, batch).compile()
    hlo_text = compiled.as_text()
    ops = xa.parse_hlo_collectives(hlo_text, mesh)
    cost = compiled_cost_analysis(compiled)
    flops = float(cost.get("flops", 0.0)) if cost else None
    flops = flops if flops and flops > 0 else None
    bytes_accessed = float(cost.get("bytes accessed", 0.0)) if cost else None

    step_stats, (params, opt_state, *_rest) = measure_step(
        compiled, (params, opt_state, batch),
        reps=reps, warmup=warmup, rebind=True, return_args=True,
    )

    compute_stats = None
    compute_error = None
    try:
        if n_chips == 1:
            # one chip: the measured program has no collectives — its
            # re-timing IS the compute-only counterfactual
            compute_stats, (params, opt_state, *_rest) = measure_step(
                compiled, (params, opt_state, batch),
                reps=reps, warmup=1, rebind=True, return_args=True,
            )
        else:
            from ddl25spring_tpu.benchmarks import (
                build_compute_counterfactual,
            )

            pcb = per_chip_batch or int(meta["batch"]) // n_chips
            s1, p1, o1, _m1 = build_compute_counterfactual(devices, pcb)
            raw1 = (
                jnp.zeros((pcb, 32, 32, 3), jnp.uint8),
                jnp.zeros((pcb,), jnp.int32),
            )
            c1 = s1.lower(p1, o1, raw1).compile()
            compute_stats = measure_step(
                c1, (p1, o1, raw1), reps=reps, warmup=warmup, rebind=True
            )
    except Exception as e:  # noqa: BLE001 — the counterfactual must
        # never cost the step measurement itself
        compute_error = f"{type(e).__name__}: {e}"

    benches, site_keys = build_micro_benches(mesh, ops)
    costs = time_micro_benches(benches, reps=micro_reps)
    micro = micro_site_records(ops, site_keys, costs)
    wire_total = sum(
        t["wire_bytes"] for t in xa.collective_totals(ops).values()
    )
    # the schedule verifier's analytical overlap ceiling for the LIVE
    # bench step (same discipline rule as the registry strategies:
    # overlapped bucket emission -> dataflow windows, else the
    # committed schedule's windows)
    static_bound = None
    try:
        from ddl25spring_tpu.analysis import sched as sched_mod

        static_bound = sched_mod.analyze_schedule(
            hlo_text, mesh, ops=ops,
            discipline="overlap" if meta.get("overlap") else "sync",
        ).get("static_overlap_bound")
    except Exception:  # noqa: BLE001 — the bound must never cost the
        static_bound = None  # measurement itself
    record = build_record(
        strategy=f"bench-{meta['layout']}",
        mesh_axes={
            ax: int(s) for ax, s in zip(mesh.axis_names, mesh.devices.shape)
        },
        n_chips=n_chips,
        step=step_stats,
        compute=compute_stats,
        compute_error=compute_error,
        micro=micro,
        flops=flops,
        bytes_accessed=bytes_accessed,
        wire_bytes=wire_total,
        device=meta.get("device"),
        extra={
            "batch": int(meta.get("batch", 0)) or None,
            "bucket_bytes": meta.get("bucket_bytes"),
            "static_overlap_bound": static_bound,
            **({"overlap": True} if meta.get("overlap") else {}),
        },
    )
    return record, params, opt_state


# ------------------------------------------------------------------ ledger


def append_ledger(
    record: dict[str, Any], path: str | None = None
) -> str:
    """Append one record to the JSONL ledger (created on first use)."""
    path = path or DEFAULT_LEDGER
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(record, default=str) + "\n")
    return path


def read_ledger(path: str | None = None) -> list[dict[str, Any]]:
    """All parseable records, in append order.  A torn trailing line
    (killed mid-write) is skipped, never fatal — the ledger must stay
    readable through the exact crashes it exists to diagnose."""
    path = path or DEFAULT_LEDGER
    out: list[dict[str, Any]] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and rec.get("record") == "perf":
                out.append(rec)
    return out


def write_run_perf(record: dict[str, Any], run_dir: str) -> str:
    """Drop the record as ``<run_dir>/perf.json`` — the artifact
    ``obs/report.py`` folds into its "performance" section."""
    os.makedirs(run_dir, exist_ok=True)
    path = os.path.join(run_dir, PERF_BASENAME)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1, default=str)
    os.replace(tmp, path)
    return path


# --------------------------------------------------------------------- CLI


def main(argv=None) -> int:
    import argparse

    import jax

    # env alone is too late on images whose sitecustomize registers a
    # TPU plugin at interpreter start; the config call forces CPU
    jax.config.update("jax_platforms", "cpu")

    from ddl25spring_tpu.obs.compile_report import (
        DEFAULT_STRATEGIES,
        parse_mesh_arg,
    )

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--strategy", default="dp",
                    help="comma-separated strategy names, or 'all' "
                         f"(known: {', '.join(DEFAULT_STRATEGIES)})")
    ap.add_argument("--mesh", default=None,
                    help="mesh sizes like 2x4, positional onto each "
                         "strategy's axis names")
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--micro-reps", type=int, default=5)
    ap.add_argument("--rounds", type=int, default=1,
                    help="records per strategy; rounds >= 2 re-time the "
                         "same compiled programs, giving perf_report "
                         "--check a same-process baseline")
    ap.add_argument("--ledger", default=DEFAULT_LEDGER, metavar="JSONL",
                    help=f"append records here (default {DEFAULT_LEDGER}; "
                         "'-' disables)")
    ap.add_argument("--no-counterfactual", action="store_true",
                    help="skip the 1-device compute-only measurement")
    args = ap.parse_args(argv)

    names = (
        list(DEFAULT_STRATEGIES) if args.strategy == "all"
        else [s.strip() for s in args.strategy.split(",") if s.strip()]
    )
    rc = 0
    for name in names:
        try:
            records = measure_strategy(
                name, parse_mesh_arg(args.mesh),
                reps=args.reps, warmup=args.warmup,
                micro_reps=args.micro_reps, rounds=args.rounds,
                compute_counterfactual=not args.no_counterfactual,
            )
        except Exception as e:  # noqa: BLE001 — degrade per strategy
            print(json.dumps({
                "record": "perf", "strategy": name,
                "error": f"{type(e).__name__}: {e}",
            }))
            rc = 1
            continue
        for rec in records:
            if args.ledger != "-":
                append_ledger(rec, args.ledger)
            print(json.dumps(rec, default=str))
    return rc


if __name__ == "__main__":
    import sys

    from ddl25spring_tpu.utils.platform import ensure_cpu_tools_env

    ensure_cpu_tools_env()
    sys.exit(main())
