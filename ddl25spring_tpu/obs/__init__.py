"""Run telemetry that works everywhere the framework runs.

The framework's perf story previously rested on two instruments: manual
``perf_counter`` segments and the ``jax.profiler`` device tracer — and the
tracer hangs indefinitely on tunneled TPU transports (RESULTS §6a), which
is exactly the environment the benchmarks run in.  This package is the
always-on, low-overhead substrate that does not depend on the XLA profiler
being usable:

- :mod:`~ddl25spring_tpu.obs.spans` — host-side nested span tracer
  producing Chrome-trace/Perfetto JSON (and mirroring every span into
  ``jax.profiler.TraceAnnotation`` so it shows inside real device traces
  when those work);
- :mod:`~ddl25spring_tpu.obs.logger` — append-only JSONL step metrics with
  a run-metadata header (mesh, layout, git sha, jax version);
- :mod:`~ddl25spring_tpu.obs.counters` — values from INSIDE jitted
  programs via ``jax.debug.callback`` (MoE aux/load stats, pipeline tick
  cadence, ZeRO collective bytes);
- ``tools/obs_report.py`` — folds a run directory into a summary table
  (steps/sec p50/p95, MFU, bubble fraction, h2d bandwidth);
- :mod:`~ddl25spring_tpu.obs.perfscope` — steady-state measurement
  harness (imported on demand, not re-exported here): barriered step
  wall p50/p95, a one-device compute-only counterfactual, standalone
  micro-costs per collective-inventory site, measured MFU against the
  calibrated chip peak, and the cross-run regression ledger
  (``runs/perf_ledger.jsonl`` + ``tools/perf_report.py --check``).

Runtime health (the operable half — the compile-time analytics'
runtime counterpart):

- :mod:`~ddl25spring_tpu.obs.sentinels` — in-step numerics sentinels
  (loss / grad global-norm / non-finite leaves / update ratio computed
  INSIDE the compiled step; policy log/halt/skip on violation; gated by
  ``DDL25_SENTINELS`` with the same HLO-identical-when-disabled pin);
- :mod:`~ddl25spring_tpu.obs.recorder` — crash-surviving flight
  recorder (ring buffer of the last N step records, dumped as
  ``flight.json`` on unhandled exception / SIGTERM / atexit);
- :mod:`~ddl25spring_tpu.obs.watchdog` — stall watchdog (fires when no
  step completes within a deadline; dumps all host thread stacks plus
  the flight record);
- :mod:`~ddl25spring_tpu.obs.timeline` — graft-trace: the unified run
  timeline (typed append-only ``timeline.jsonl`` every subsystem emits
  into: serve request lifecycles with virtual + wall clocks, chaos
  fires, reshape windows, autosave, watchdog, sentinel violations —
  merged with spans + flight into one Perfetto trace by
  ``tools/trace_export.py``).

Everything is gated by one trace-time flag (:mod:`~ddl25spring_tpu.obs.
state`): disabled (the default), instrumented step functions lower to HLO
identical to uninstrumented ones — zero cost, pinned in
``tests/test_obs.py``.  Enable with ``DDL25_OBS=1`` or ``obs.enable()``
*before* building/tracing the step.
"""

from ddl25spring_tpu.obs import sentinels
from ddl25spring_tpu.obs.counters import (
    CounterSet,
    counters,
    gpipe_bubble_fraction,
)
from ddl25spring_tpu.obs.recorder import FlightRecorder, flight
from ddl25spring_tpu.obs.sentinels import SentinelViolation
from ddl25spring_tpu.obs.watchdog import StallWatchdog, thread_stacks
from ddl25spring_tpu.obs.logger import (
    MetricsLogger,
    iter_jsonl,
    read_jsonl,
    run_metadata,
)
from ddl25spring_tpu.obs.spans import (
    SpanRecorder,
    get_recorder,
    instant,
    set_recorder,
    span,
)
from ddl25spring_tpu.obs.state import enable, enabled, scoped
from ddl25spring_tpu.obs.timeline import Timeline, timeline

# compile-time analytics (obs/xla_analytics.py, obs/compile_report.py) are
# imported lazily by their consumers — they pull in the parallel stack and
# must not tax `import ddl25spring_tpu.obs` on the hot bench path.

__all__ = [
    "CounterSet",
    "FlightRecorder",
    "MetricsLogger",
    "SentinelViolation",
    "SpanRecorder",
    "StallWatchdog",
    "Timeline",
    "timeline",
    "counters",
    "flight",
    "sentinels",
    "thread_stacks",
    "enable",
    "enabled",
    "get_recorder",
    "gpipe_bubble_fraction",
    "instant",
    "iter_jsonl",
    "read_jsonl",
    "run_metadata",
    "scoped",
    "set_recorder",
    "span",
]
