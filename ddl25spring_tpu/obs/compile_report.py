"""Build multi-strategy compile reports (the no-TPU perf instrument).

Front end over :mod:`ddl25spring_tpu.obs.xla_analytics`: compile every
registered parallel strategy (or the bench workload itself) on a fake
CPU mesh and collect the per-strategy reports — collective inventory,
peak-HBM estimate, FLOP totals, roofline projections, and signature
violations — into one JSON document.  Three consumers:

- ``bench.py`` attaches the bench-workload report to its BENCH line's
  ``telemetry`` dict *before* probing the device, so a dead-TPU run
  still yields analyzable perf data (the r01–r05 failure mode);
- ``tools/comms_report.py`` renders the human table and gates CI on
  signature drift;
- ``obs/report.py`` folds a ``compile_report.json`` found in a run
  directory into the telemetry summary.

Run directly (prints JSON to stdout; CPU-only, sets its own fake device
count)::

    python -m ddl25spring_tpu.obs.compile_report --strategies dp,zero3
    python -m ddl25spring_tpu.obs.compile_report --bench

A strategy that cannot trace/compile on the running jax (e.g. the
homogeneous-pipeline grad path pre-VMA) reports ``{"error": ...}`` for
its entry and never takes the others down.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Any

COMPILE_REPORT_BASENAME = "compile_report.json"

# every registered strategy, in report order — the full fourteen.  The
# sched verifier (PR 9) pins each *-overlap strategy's static overlap
# bound strictly above its sync twin's, which needs BOTH twins compiled
# under every gate (signature pins, graft-lint H008-H010, perfscope);
# zero1/zero2's overlap twins therefore graduated from on-demand to
# default.  PR 10 adds the two serving programs (serve-decode /
# serve-prefill: the paged-KV TP inference steps, pinned all-reduce-only
# like tp but forward-only); PR 11 adds the prefix cache's start-offset
# prefill variant (serve-prefill-cached), whose SHORTER scan — fewer
# all-reduces than serve-prefill's — is the compile-time proof of the
# prefill FLOPs a radix hit skips.  PR 12 adds the two partition-rule-
# table strategies (dp-rules / zero3-rules: the strategy is a mesh +
# regex rule table + issue discipline, parallel/rules.py), pinned
# bitwise-identical to their bespoke twins and coverage-proven by the
# sharding-flow verifier (analysis/shard_flow.py, H011-H013).  PR 13
# adds the speculative-decoding pair (serve-draft / serve-verify: the
# tiny-LLaMA drafter's k-token scan over its own paged pool and the
# target's width-(k+1) verify pass, serve/spec.py).  All twenty-one
# share the tests' lower-once compile cache, so tier-1 pays each
# compile exactly once.
DEFAULT_STRATEGIES = (
    "dp", "dp-overlap", "dp-rules", "zero1", "zero1-overlap", "zero2",
    "zero2-overlap", "zero3", "zero3-prefetch", "zero3-overlap",
    "zero3-rules", "pipeline", "het_pipeline", "tp", "sp", "ep",
    "serve-decode", "serve-prefill", "serve-prefill-cached",
    "serve-draft", "serve-verify",
    "serve-decode-tp", "serve-prefill-tp", "serve-decode-zero3stream",
)


def parse_mesh_arg(mesh: str | None) -> tuple[int, ...] | None:
    """The shared ``--mesh 2x4`` CLI syntax (positional onto a
    strategy's axis names; extras fold into the last axis)."""
    if not mesh:
        return None
    return tuple(int(x) for x in mesh.lower().split("x"))


def build_compile_report(
    strategies: tuple[str, ...] | list[str] | None = None,
    mesh_sizes: tuple[int, ...] | None = None,
) -> dict[str, Any]:
    """Compile + analyze each named strategy (default: all registered).
    ``mesh_sizes`` applies to every strategy (positional onto its axis
    names); None takes each strategy's default mesh."""
    import jax

    from ddl25spring_tpu.obs import xla_analytics

    report: dict[str, Any] = {
        "record": "compile_report",
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "strategies": {},
    }
    for name in strategies or DEFAULT_STRATEGIES:
        report["strategies"][name] = xla_analytics.compile_strategy(
            name, mesh_sizes
        )
    return report


def bench_compile_report(
    dp: int = 2,
    stages: int = 2,
    microbatches: int = 2,
    per_chip_batch: int = 64,
) -> dict[str, Any]:
    """Compile report for the BASELINE.json bench workload itself: the
    ResNet-18/CIFAR-10 train steps ``benchmarks.build_resnet_step``
    produces, lowered on a fake CPU mesh at a REDUCED batch (collective
    structure and grad bytes are batch-invariant for DP; compile time is
    not).  Two entries: ``bench-dp`` (pure DP) and ``bench-dppp`` (the
    DPxPP het pipeline — on pre-VMA jax its grad path cannot trace, and
    the entry degrades to an error string, which is itself signal)."""
    import jax

    from ddl25spring_tpu.obs import xla_analytics

    devices = jax.devices("cpu")
    report: dict[str, Any] = {
        "record": "compile_report",
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "note": f"bench workload lowered at per_chip_batch={per_chip_batch} "
                "(reduced for CPU compile time; DP collective payloads are "
                "batch-invariant)",
        "strategies": {},
    }

    def entry(name, dp_n, S, M):
        from ddl25spring_tpu.benchmarks import build_resnet_step

        n = dp_n * S
        if len(devices) < n:
            return {"strategy": name,
                    "error": f"needs {n} CPU devices, have {len(devices)}"}
        batch = per_chip_batch * n
        try:
            step, params, opt_state, meta = build_resnet_step(
                devices[:n], dp_n, S, M, batch, instrument=False
            )
            import jax.numpy as jnp

            raw = (
                jnp.zeros((batch, 32, 32, 3), jnp.uint8),
                jnp.zeros((batch,), jnp.int32),
            )
            compiled = step.lower(params, opt_state, raw).compile()
            mesh = meta["mesh"]
            hlo_text = compiled.as_text()
            r = xla_analytics.analyze_compiled(compiled, mesh, hlo_text=hlo_text, meta={
                "layout": meta["layout"],
                "topology": meta["topology"],
                "n_chips": meta["n_chips"],
                "batch": batch,
            })
            r["strategy"] = name
            r["mesh"] = {
                ax: int(s)
                for ax, s in zip(mesh.axis_names, mesh.devices.shape)
            }
            r["lowered"] = "train_step"
            r["donation"]["donatable_leaves"] = len(
                jax.tree.leaves((params, opt_state))
            )
            # hazard findings ride the report into the BENCH line's
            # telemetry, so a dead-TPU run still says e.g. "44 MiB sync
            # all-reduce, no overlap" about the exact program it ran
            xla_analytics.attach_findings(
                r, compiled, strategy=name, hlo_text=hlo_text
            )
            return r
        except Exception as e:  # noqa: BLE001 — degrade per entry
            return {"strategy": name, "error": f"{type(e).__name__}: {e}"}

    report["strategies"]["bench-dp"] = entry("bench-dp", dp, 1, 1)
    report["strategies"]["bench-dppp"] = entry(
        "bench-dppp", dp, stages, microbatches
    )
    return report


def write_compile_report(run_dir: str, report: dict[str, Any]) -> str:
    """Persist a report as ``<run_dir>/compile_report.json`` (the file
    ``obs/report.py`` and ``tools/obs_report.py`` pick up)."""
    os.makedirs(run_dir, exist_ok=True)
    path = os.path.join(run_dir, COMPILE_REPORT_BASENAME)
    with open(path, "w") as f:
        json.dump(report, f, indent=1, default=str)
    return path


def bench_compile_report_subprocess(
    timeout_s: float = 600.0,
) -> dict[str, Any]:
    """Run :func:`bench_compile_report` in a fresh CPU-only subprocess.

    ``bench.py``'s parent driver cannot compute the report in-process:
    its jax must stay free to dial the TPU backend, while the report
    needs ``JAX_PLATFORMS=cpu`` plus a multi-device fake-host flag — both
    of which are interpreter-start decisions.  A subprocess gives the
    report its own interpreter and keeps a report-side crash from
    costing the bench."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", DDL25_OBS="")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4"
        ).strip()
    try:
        r = subprocess.run(
            [sys.executable, "-m", "ddl25spring_tpu.obs.compile_report",
             "--bench"],
            capture_output=True, text=True, timeout=timeout_s, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
        )
    except subprocess.TimeoutExpired:
        return {"error": f"compile-report subprocess exceeded {timeout_s:.0f}s"}
    if r.returncode != 0:
        return {"error": "compile-report subprocess failed rc="
                         f"{r.returncode}: {(r.stderr or '')[-500:]}"}
    parsed = last_json_dict_line(r.stdout)
    if parsed is None:
        return {"error": "compile-report subprocess printed no JSON"}
    return parsed


def last_json_dict_line(stdout: str) -> dict[str, Any] | None:
    """The last stdout line that parses as a JSON *dict* (the driver
    contract both the bench children and the compile-report subprocess
    print) — stray printables and non-dict JSON are skipped.  Shared by
    ``bench.py``'s retry driver and the subprocess wrapper above."""
    for line in reversed(stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict):
            return parsed
    return None


def main(argv=None) -> int:
    import argparse

    import jax

    # env alone is too late on images whose sitecustomize registers a
    # TPU plugin at interpreter start (the exact no-accelerator scenario
    # this tool serves); the config call forces CPU regardless
    jax.config.update("jax_platforms", "cpu")

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--strategies", default=None,
                    help="comma-separated strategy names "
                         f"(default: {','.join(DEFAULT_STRATEGIES)})")
    ap.add_argument("--mesh", default=None,
                    help="mesh sizes like 2x4, positional onto each "
                         "strategy's axis names")
    ap.add_argument("--bench", action="store_true",
                    help="report on the bench workload (ResNet DP / DPxPP) "
                         "instead of the strategy registry")
    ap.add_argument("--out", default=None, metavar="DIR",
                    help="also write DIR/compile_report.json")
    args = ap.parse_args(argv)

    mesh_sizes = parse_mesh_arg(args.mesh)
    if args.bench:
        report = bench_compile_report()
    else:
        names = (
            tuple(s.strip() for s in args.strategies.split(",") if s.strip())
            if args.strategies else None
        )
        report = build_compile_report(names, mesh_sizes)
    if args.out:
        write_compile_report(args.out, report)
    print(json.dumps(report, default=str))
    return 0


if __name__ == "__main__":
    # CPU-only, multi-device fake host — decided before any backend init
    from ddl25spring_tpu.utils.platform import ensure_cpu_tools_env

    ensure_cpu_tools_env()
    sys.exit(main())
