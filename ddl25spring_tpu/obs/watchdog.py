"""Stall watchdog: turn a silent hang into a stack-attributed dump.

The framework's worst observed failure mode is not a crash but a
*wedge*: ``jax.devices()`` dialing a dead TPU tunnel blocks forever
(BENCH r01–r05 all ended as a bare ``device init timed out`` string),
and a mid-run collective on a flaky link can stall a step indefinitely.
Pod-scale practice treats stalls as routine events the framework itself
must detect (Podracer, arXiv:2104.06272).  This module is that
detector: a daemon monitor thread that fires when no progress beat
arrives within a deadline, captures **every host thread's Python
stack** (``sys._current_frames`` — it sees the wedged thread exactly
where it is blocked), and persists it through the flight recorder, so
the post-mortem names the blocking frame instead of the timeout.

Progress is whatever the caller defines: :meth:`StallWatchdog.beat`
directly, or any :meth:`~ddl25spring_tpu.obs.recorder.FlightRecorder.
record`/``beat`` on the shared flight ring (the default source) — the
sentinel callbacks and ``benchmarks.timed_run`` already beat it every
step, so an instrumented run gets stall coverage for free.

Host-only by construction: nothing here enters a traced program, so
the HLO-identity contract is untouched.
"""

from __future__ import annotations

import contextlib
import sys
import threading
import traceback
import time
from typing import Any, Callable

from ddl25spring_tpu.analysis.host_sanitizer import wrap_lock
from ddl25spring_tpu.obs.recorder import (
    flight,
    watchdog_deadline_default,
)


def thread_stacks() -> dict[str, list[str]]:
    """Format every live host thread's current Python stack.  Keys are
    ``"name (tid)"``; values are ``file:line in func`` frame lists,
    innermost last — the shape a human (or the next session) reads."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: dict[str, list[str]] = {}
    for tid, frame in sys._current_frames().items():
        label = f"{names.get(tid, 'unknown')} (tid={tid})"
        out[label] = [
            f"{fs.filename}:{fs.lineno} in {fs.name}"
            + (f"\n    {fs.line}" if fs.line else "")
            for fs in traceback.extract_stack(frame)
        ]
    return out


class StallWatchdog:
    """Fire once when no step completes within ``deadline_s``.

    Usage — wrap any phase that must keep making progress::

        with StallWatchdog(deadline_s=600, name="train") as wd:
            for step in range(n):
                run_one_step()
                wd.beat()
        if wd.fired:
            ...  # wd.dump_path holds the stack-attributed flight dump

    ``source="flight"`` (default) also counts any activity on the shared
    flight recorder as progress, so sentinel callbacks and instrumented
    ``timed_run`` loops feed it without plumbing.  The monitor is a
    daemon thread: a fired (or forgotten) watchdog can never keep the
    process alive.  It fires ONCE per stall episode (the dump is not
    repeated while the same stall drags on) and re-arms as soon as real
    progress resumes — from ``beat()`` or any watched-source activity —
    so a second stall later in the same run fires again.
    """

    def __init__(
        self,
        deadline_s: float | None = None,
        run_dir: str | None = None,
        name: str = "run",
        source: str = "flight",
        on_fire: Callable[[dict], Any] | None = None,
        poll_s: float | None = None,
    ):
        self.deadline_s = float(
            deadline_s if deadline_s is not None
            else watchdog_deadline_default()
        )
        if self.deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0, got {self.deadline_s}"
            )
        self.name = name
        self.run_dir = run_dir
        self.source = source
        self.on_fire = on_fire
        self.poll_s = poll_s or min(1.0, self.deadline_s / 4)
        self.fired = False
        self.fire_count = 0
        self.dump_path: str | None = None
        # guards the beat/fired transitions: beat() runs on the main
        # thread, the re-arm and fire run on the monitor — graft-race
        # S201 caught the unsynchronized test-and-set.  Never held
        # across the dump (which can block on I/O for seconds).
        self._state_lock = wrap_lock(
            "watchdog._state_lock", threading.Lock()
        )
        self._last_beat = time.perf_counter()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ---- lifecycle ------------------------------------------------------

    def start(self) -> "StallWatchdog":
        if self._thread is not None:
            return self
        self._stop.clear()  # a stopped watchdog must be restartable
        self.beat()
        self._thread = threading.Thread(
            target=self._monitor,
            name=f"stall-watchdog[{self.name}]",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.poll_s)
            self._thread = None

    def __enter__(self) -> "StallWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def beat(self) -> None:
        with self._state_lock:
            self._last_beat = time.perf_counter()
            self.fired = False  # re-arm after a fire

    # ---- monitor --------------------------------------------------------

    def _idle_s(self) -> float:
        with self._state_lock:
            idle = time.perf_counter() - self._last_beat
        if self.source == "flight":
            idle = min(idle, flight.seconds_since_beat())
        return idle

    def _monitor(self) -> None:
        while not self._stop.wait(self.poll_s):
            idle = self._idle_s()
            if self.fired:
                # one dump per stall episode; REAL progress (our stall
                # record doesn't touch the flight clock) re-arms so the
                # next stall in the same run fires again
                if idle < self.deadline_s:
                    with self._state_lock:
                        self.fired = False
                continue
            if idle >= self.deadline_s:
                self._fire()

    def _fire(self) -> None:
        with self._state_lock:
            self.fired = True
            self.fire_count += 1
        info = {
            "watchdog": self.name,
            "deadline_s": self.deadline_s,
            "idle_s": round(self._idle_s(), 3),
            "fired_at_unix": time.time(),
        }
        stacks = thread_stacks()
        flight.record(kind="stall", touch=False, **info,
                      threads=len(stacks))
        try:
            self.dump_path = flight.dump(
                path=(
                    None if self.run_dir is None
                    else f"{self.run_dir}/flight.json"
                ),
                reason="stall",
                extra={"stall": info, "thread_stacks": stacks},
            )
            where = self.dump_path
        except Exception as e:  # noqa: BLE001 — keep the stderr alert
            where = f"<dump failed: {e}>"
        print(
            f"[stall-watchdog:{self.name}] no step completed in "
            f"{self.deadline_s:.0f}s — {len(stacks)} host thread stacks "
            f"dumped to {where}",
            file=sys.stderr,
        )
        if self.on_fire is not None:
            with contextlib.suppress(Exception):
                self.on_fire(dict(info, dump_path=self.dump_path))
