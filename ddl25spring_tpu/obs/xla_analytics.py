"""Compile-time XLA analytics: collective accounting from optimized HLO.

Runtime telemetry (:mod:`ddl25spring_tpu.obs`) only speaks when a device
is reachable — and every BENCH round so far died at the tunnel
(``accelerator unreachable``).  This module extracts the perf facts that
do NOT need hardware: lower a strategy's train step under a fake
``make_mesh`` on CPU, walk the *optimized* HLO of the compiled program,
and account for every cross-device collective — kind, payload bytes,
mesh axes (recovered from replica groups), and **execution count**
(collectives inside ``lax.scan``/``while`` bodies multiply by the loop's
``known_trip_count``, which XLA annotates on optimized while ops).
Paired with ``compiled.memory_analysis()`` / ``cost_analysis()`` (via
:mod:`ddl25spring_tpu.utils.compat`, which papers over the jax 0.4.x API
shapes), one :func:`analyze_compiled` call yields the collective
inventory, a peak-HBM estimate, FLOP totals, and roofline projections
per chip spec — all on a machine with no accelerator at all.

The strategy registry at the bottom maps each parallelism strategy the
framework implements (DP, ZeRO-1/2/3, pipeline, het-pipeline, TP, SP,
EP) to the ``describe()`` hook its ``parallel/`` module exposes; a
strategy's ``describe()`` returns the lowerable step + example inputs +
its *analytic* collective signature, so :func:`check_signature` can pin
"plain DP is exactly grad-bytes of all-reduce over the data axis and
nothing else" as a CPU-green tier-1 test — any refactor that silently
adds a stray all-gather or breaks fusion fails CI before it ever
reaches a TPU (the comms-regression pinning contract; see
``tests/test_xla_analytics.py`` and ``tools/comms_report.py``).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Any

# ------------------------------------------------------------------ HLO text

# bytes per element for the HLO primitive types that can appear in
# collective result shapes
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "tf32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
}

_COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
    "collective-broadcast",
)

# `%all-reduce.5 = f32[16,4]{1,0} all-reduce(...)`: the opcode is the bare
# token before `(`; operand *references* are `%`-prefixed, so `(?<!%)`
# keeps `all-reduce(f32[] %all-reduce.3)` from double-counting.  Async
# pairs count at `-start` and never at `-done`.
_COLLECTIVE_RE = re.compile(
    r"(?<![%\w])(" + "|".join(_COLLECTIVE_KINDS) + r")(-start)?\("
)

_SHAPE_RE = re.compile(r"\b([a-z]\w*)\[([\d,]*)\]")

_COMP_HEADER_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->")

# call-site attributes that transfer control to another computation
_CALLEE_RE = re.compile(
    r"(?:body|condition|to_apply|true_computation|false_computation)="
    r"%?([\w.\-]+)"
)
_BRANCHES_RE = re.compile(r"branches=\{([^}]*)\}")
_TRIP_RE = re.compile(r'known_trip_count[\\"=:{\s]+n[\\"=:\s]+(\d+)')

_SHARDING_TILE_RE = re.compile(r"devices=\[([\d,]+)\]")
_LAST_TILE_DIMS_RE = re.compile(r"last_tile_dims=\{([^}]*)\}")


def _shape_bytes(type_str: str) -> int:
    """Total bytes of an HLO result type string (handles tuples by summing
    every ``dtype[dims]`` group it contains)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        if dtype not in _DTYPE_BYTES:
            continue
        elems = math.prod(int(d) for d in dims.split(",") if d) if dims else 1
        total += elems * _DTYPE_BYTES[dtype]
    return total


@dataclass
class _Comp:
    name: str
    lines: list[str] = field(default_factory=list)
    is_entry: bool = False


def _split_computations(hlo_text: str) -> tuple[dict[str, _Comp], str | None]:
    """Split optimized-HLO text into named computations.  Returns
    ``(computations, entry_name)``."""
    comps: dict[str, _Comp] = {}
    cur: _Comp | None = None
    entry = None
    for raw in hlo_text.splitlines():
        line = raw.strip()
        m = _COMP_HEADER_RE.match(line)
        if m and line.endswith("{"):
            cur = _Comp(m.group(2), is_entry=bool(m.group(1)))
            comps[cur.name] = cur
            if cur.is_entry:
                entry = cur.name
            continue
        if line == "}":
            cur = None
            continue
        if cur is not None and "=" in line:
            cur.lines.append(line)
    return comps, entry


def _execution_multipliers(
    comps: dict[str, _Comp], entry: str | None
) -> tuple[dict[str, int], dict[str, bool]]:
    """How many times each computation executes per entry invocation.

    Whiles multiply their body/condition by the optimizer-annotated
    ``known_trip_count``; calls/reducers/branches inherit the caller's
    count (a conditional branch runs *at most* once per visit — counted
    as once, the upper bound the signature pins care about).  Returns
    ``(multiplier, trip_known)`` — ``trip_known[c]`` is False anywhere a
    while without a recoverable trip count encloses ``c``.
    """
    mult: dict[str, int] = {}
    known: dict[str, bool] = {}
    if entry is None:
        return mult, known

    def visit(name: str, m: int, k: bool) -> None:
        comp = comps.get(name)
        if comp is None:
            return
        mult[name] = mult.get(name, 0) + m
        known[name] = known.get(name, True) and k
        for line in comp.lines:
            callees = _CALLEE_RE.findall(line)
            br = _BRANCHES_RE.search(line)
            if br:
                callees += [c.strip().lstrip("%") for c in br.group(1).split(",")]
            if not callees:
                continue
            if "= " in line and " while(" in line:
                t = _TRIP_RE.search(line)
                trip = int(t.group(1)) if t else 1
                for c in callees:
                    visit(c, m * trip, k and t is not None)
            else:
                for c in callees:
                    visit(c, m, k)

    visit(entry, 1, True)
    return mult, known


def _op_name_of_line(line: str) -> str | None:
    """The ``%name`` an HLO instruction line defines (sans ``%``)."""
    m = re.match(r"(?:ROOT\s+)?%([\w.\-]+)\s*=", line)
    return m.group(1) if m else None


def _operand_names(line: str, open_paren: int) -> list[str]:
    """``%``-operand references inside the balanced-paren argument list
    starting at ``line[open_paren]`` (skips attribute references like
    ``to_apply=%add`` that sit after the closing paren)."""
    depth = 0
    end = len(line)
    for i in range(open_paren, len(line)):
        c = line[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                end = i
                break
    return re.findall(r"%([\w.\-]+)", line[open_paren:end])


def parse_op_defs(hlo_text: str) -> dict[str, dict[str, dict[str, Any]]]:
    """Per-computation def table: ``{comp_name: {op_name: def}}`` where
    each def is ``{"opcode", "type", "operands", "root", "line"}``.

    This is the substrate the hazard rules walk — e.g. "is this f32
    collective fed by a bf16 ``convert``" (H004) or "does an all-gather
    feed a reduce-scatter" (H002) are producer-chain questions over
    these defs (:mod:`ddl25spring_tpu.analysis.engine`).
    """
    comps, _entry = _split_computations(hlo_text)
    out: dict[str, dict[str, dict[str, Any]]] = {}
    for comp in comps.values():
        defs: dict[str, dict[str, Any]] = {}
        for line in comp.lines:
            name = _op_name_of_line(line)
            if name is None:
                continue
            rhs = line.split("=", 1)[1].strip()
            # result type: a tuple type spans balanced parens; otherwise
            # it's the first space-free token
            if rhs.startswith("("):
                depth = 0
                tend = 0
                for i, c in enumerate(rhs):
                    if c == "(":
                        depth += 1
                    elif c == ")":
                        depth -= 1
                        if depth == 0:
                            tend = i + 1
                            break
                type_str, rest = rhs[:tend], rhs[tend:].lstrip()
            else:
                type_str, _, rest = rhs.partition(" ")
            om = re.match(r"([\w.\-]+)\(", rest)
            if not om:
                continue
            opcode = om.group(1)
            paren = line.find(rest, line.index("=")) + om.end() - 1
            defs[name] = {
                "opcode": opcode,
                "type": type_str,
                "operands": _operand_names(line, paren),
                "root": line.startswith("ROOT "),
                "line": line,
            }
        out[comp.name] = defs
    return out


def _sharding_attr_of_line(line: str) -> str | None:
    """The brace-balanced body of a ``sharding={...}`` op attribute
    (``last_tile_dims={...}`` nests braces, so a ``[^}]*`` regex would
    truncate it)."""
    start = line.find("sharding={")
    if start < 0:
        return None
    i = line.index("{", start)
    depth = 0
    for j in range(i, len(line)):
        if line[j] == "{":
            depth += 1
        elif line[j] == "}":
            depth -= 1
            if depth == 0:
                return line[i + 1:j]
    return None


def parse_sharding(attr: str | None) -> dict[str, Any] | None:
    """Structured view of one HLO ``sharding=`` annotation body — the
    substrate the sharding-flow verifier walks
    (:mod:`ddl25spring_tpu.analysis.shard_flow`).

    Returns ``{"raw", "replicated", "maximal", "manual", "tile"``
    (the ``devices=[...]`` tile-assignment dims), ``"trailing_subgroups"``
    (trailing tile dims that replicate/are manual rather than partition
    data dims), ``"partitioned_dims"`` (data-dim indices with >1
    partition) and ``"partitions"`` (per partitioned dim, its factor)}``
    — or None when the op carries no annotation.  A rank change between
    the global and the per-device local shape never matters here: the
    tile dims index GLOBAL data dimensions.
    """
    if attr is None:
        return None
    out: dict[str, Any] = {
        "raw": attr,
        "replicated": attr.strip() == "replicated",
        "maximal": attr.strip().startswith("maximal"),
        "manual": attr.strip() == "manual",
        "tile": None,
        "trailing_subgroups": 0,
        "partitioned_dims": [],
        "partitions": {},
    }
    m = _SHARDING_TILE_RE.search(attr)
    if not m:
        return out
    tile = [int(x) for x in m.group(1).split(",")]
    out["replicated"] = False
    trailing = 0
    ltd = _LAST_TILE_DIMS_RE.search(attr)
    if ltd:
        trailing = len([x for x in ltd.group(1).split(",") if x.strip()])
    elif "last_tile_dim_replicate" in attr:
        trailing = 1
    out["tile"] = tile
    out["trailing_subgroups"] = trailing
    data_dims = tile[: len(tile) - trailing] if trailing else tile
    out["partitioned_dims"] = [
        i for i, d in enumerate(data_dims) if d > 1
    ]
    out["partitions"] = {
        i: d for i, d in enumerate(data_dims) if d > 1
    }
    return out


def parse_input_output_aliases(hlo_text: str) -> list[dict[str, Any]]:
    """Entries of the module-level ``input_output_alias`` table — the
    buffers XLA reuses in place (donated params/opt-state).  Each entry:
    ``{"output_index": [...], "param_number": int, "param_index": [...],
    "kind": "may-alias"|"must-alias"}``.  Empty list = nothing donated.
    """
    start = hlo_text.find("input_output_alias={")
    if start < 0:
        return []
    i = hlo_text.index("{", start)
    depth = 0
    for j in range(i, len(hlo_text)):
        if hlo_text[j] == "{":
            depth += 1
        elif hlo_text[j] == "}":
            depth -= 1
            if depth == 0:
                break
    block = hlo_text[i:j + 1]
    out = []
    for m in re.finditer(
        r"\{([\d,\s]*)\}:\s*\((\d+)\s*,\s*\{([\d,\s]*)\}\s*,?\s*([\w\-]*)\)",
        block,
    ):
        out.append({
            "output_index": [int(x) for x in m.group(1).split(",") if x.strip()],
            "param_number": int(m.group(2)),
            "param_index": [int(x) for x in m.group(3).split(",") if x.strip()],
            "kind": m.group(4) or "may-alias",
        })
    return out


def parse_entry_parameters(hlo_text: str) -> list[dict[str, Any]]:
    """The entry computation's parameters: ``{"number", "name", "bytes",
    "type", "arg"}`` per input buffer, where ``arg`` is the jax-level
    argument path XLA records in the op metadata (``params['w1']``,
    ``opt_state[0]...``, ``batch[0]``) when available — the names the
    donation-miss rule (H005) reports.  ``sharding`` is the parsed
    ``sharding=`` annotation (:func:`parse_sharding`; None when the
    parameter carries none) — the per-program layout facts the
    sharding-flow verifier's cross-program contract checks walk
    (:mod:`ddl25spring_tpu.analysis.shard_flow`, rule H013)."""
    comps, entry = _split_computations(hlo_text)
    if entry is None:
        return []
    out = []
    for line in comps[entry].lines:
        m = re.match(
            r"(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(.+?)\s*parameter\((\d+)\)", line
        )
        if not m:
            continue
        arg = re.search(r'op_name="([^"]+)"', line)
        out.append({
            "number": int(m.group(3)),
            "name": m.group(1),
            "bytes": _shape_bytes(m.group(2)),
            "type": m.group(2),
            "arg": arg.group(1) if arg else None,
            "sharding": parse_sharding(_sharding_attr_of_line(line)),
        })
    out.sort(key=lambda p: p["number"])
    return out


def _parse_groups(line: str) -> list[list[int]] | None:
    """Device groups of a collective op line.  Handles the explicit
    ``replica_groups={{0,1},{2,3}}`` form and (best-effort) the newer
    iota form ``replica_groups=[2,4]<=[8]`` / ``...<=[8]T(1,0)``."""
    m = re.search(r"replica_groups=\{(\{[\d,{}\s]*\})\}", line)
    if m:
        return [
            [int(x) for x in g.split(",") if x.strip()]
            for g in re.findall(r"\{([\d,\s]*)\}", m.group(1))
        ]
    m = re.search(
        r"replica_groups=\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?", line
    )
    if m:
        group_shape = [int(x) for x in m.group(1).split(",")]
        reshape = [int(x) for x in m.group(2).split(",")]
        total = math.prod(reshape)
        ids = list(range(total))
        try:
            import numpy as np

            arr = np.arange(total).reshape(reshape)
            if m.group(3):
                arr = arr.transpose([int(x) for x in m.group(3).split(",")])
            arr = arr.reshape(group_shape)
            return [list(map(int, row)) for row in arr]
        except Exception:  # noqa: BLE001 — malformed iota: groups unknown
            return [ids]
    return None


def _parse_pairs(line: str) -> list[tuple[int, int]] | None:
    m = re.search(r"source_target_pairs=\{([\d,{}\s]*)\}", line)
    if not m:
        return None
    return [
        tuple(int(x) for x in p.split(","))
        for p in re.findall(r"\{(\d+,\d+)\}", m.group(1))
    ]


def _mesh_coords(mesh) -> dict[int, tuple[int, ...]]:
    """device id -> mesh coordinates."""
    import numpy as np

    out = {}
    for coords in np.ndindex(*mesh.devices.shape):
        out[int(mesh.devices[coords].id)] = tuple(int(c) for c in coords)
    return out


def _axes_of_groups(groups, mesh) -> list[str]:
    """Mesh axes a collective communicates over: the axes whose coordinate
    varies within any device group (robust to any group ordering)."""
    coords = _mesh_coords(mesh)
    varying: set[int] = set()
    for g in groups:
        gc = [coords.get(d) for d in g]
        if any(c is None for c in gc) or len(gc) < 2:
            continue
        for dim in range(len(mesh.axis_names)):
            if len({c[dim] for c in gc}) > 1:
                varying.add(dim)
    return [mesh.axis_names[d] for d in sorted(varying)]


def _axes_of_pairs(pairs, mesh) -> list[str]:
    coords = _mesh_coords(mesh)
    varying: set[int] = set()
    for s, t in pairs:
        cs, ct = coords.get(s), coords.get(t)
        if cs is None or ct is None:
            continue
        for dim in range(len(mesh.axis_names)):
            if cs[dim] != ct[dim]:
                varying.add(dim)
    return [mesh.axis_names[d] for d in sorted(varying)]


def _wire_bytes(kind: str, result_bytes: int, group_size: int | None) -> int:
    """Per-device ICI traffic estimate for one execution, from the result
    bytes and participant count (ring-algorithm accounting; the numbers
    the roofline projection feeds on).  ``group_size`` None -> assume the
    worst case factor 2 for all-reduce, 1 otherwise."""
    n = group_size or 0
    if kind == "all-reduce":
        # ring all-reduce: reduce-scatter + all-gather, 2(n-1)/n x payload
        return int(2 * result_bytes * ((n - 1) / n if n > 1 else 1))
    if kind == "all-gather":
        # result is the gathered buffer; each device receives (n-1)/n of it
        return int(result_bytes * ((n - 1) / n if n > 1 else 1))
    if kind == "reduce-scatter":
        # result is the scattered shard; each device sends (n-1) shards
        return int(result_bytes * (n - 1 if n > 1 else 1))
    if kind == "all-to-all":
        # result bytes re-partitioned: (n-1)/n of it crosses the wire
        return int(result_bytes * ((n - 1) / n if n > 1 else 1))
    # collective-permute / broadcast: one payload per hop
    return int(result_bytes)


def parse_hlo_collectives(hlo_text: str, mesh=None) -> list[dict[str, Any]]:
    """Extract every collective op from optimized-HLO text.

    Returns one record per op *site*: ``{kind, result_bytes, dtype``
    (primary element type of the result), ``count``
    (executions per call, loop trip counts folded in), ``trip_known,
    axes, group_size, wire_bytes`` (per execution), ``source, name,
    computation, operands, pairs, async}``.  ``async`` is True for
    ``-start``/``-done`` pairs (the op overlaps with compute); ``pairs``
    carries a collective-permute's raw source-target pairs; ``name`` /
    ``computation`` / ``operands`` anchor the op in the def tables of
    :func:`parse_op_defs` for the hazard rules.  ``axes`` needs ``mesh``
    (a ``jax.sharding.Mesh`` whose device ids match the compiled
    program); without it axes are ``None``.
    """
    comps, entry = _split_computations(hlo_text)
    mult, known = _execution_multipliers(comps, entry)
    out: list[dict[str, Any]] = []
    for comp in comps.values():
        m = mult.get(comp.name, 0)
        if m == 0:
            continue  # dead computation (not reachable from entry)
        for line in comp.lines:
            cm = _COLLECTIVE_RE.search(line)
            if not cm:
                continue
            kind = cm.group(1)
            type_str = line.split("=", 1)[1].split(cm.group(0), 1)[0]
            result_bytes = _shape_bytes(type_str)
            # primary element dtype of the result — what a standalone
            # re-synthesis of this op must move (obs/perfscope.py's
            # measured comms cost model keys on it)
            dm = _SHAPE_RE.search(type_str)
            dtype = dm.group(1) if dm and dm.group(1) in _DTYPE_BYTES else None
            groups = _parse_groups(line)
            pairs = _parse_pairs(line)
            axes = None
            group_size = None
            if groups:
                group_size = max(len(g) for g in groups)
                if mesh is not None:
                    axes = _axes_of_groups(groups, mesh)
            elif pairs is not None:
                # permute "group" = the cycle length; use the pair count
                # per device ring (participants = distinct sources)
                group_size = len({s for s, _ in pairs}) or None
                if mesh is not None:
                    axes = _axes_of_pairs(pairs, mesh)
            src = re.search(r'source_file="([^"]+)".*?source_line=(\d+)', line)
            open_paren = line.index("(", cm.start())
            out.append({
                "kind": kind,
                "result_bytes": result_bytes,
                "dtype": dtype,
                "count": m,
                "trip_known": known.get(comp.name, True),
                "axes": axes,
                "group_size": group_size,
                "wire_bytes": _wire_bytes(kind, result_bytes, group_size),
                "source": f"{src.group(1)}:{src.group(2)}" if src else None,
                "name": _op_name_of_line(line),
                "computation": comp.name,
                "operands": _operand_names(line, open_paren),
                "pairs": pairs,
                "async": bool(cm.group(2)),
            })
    return out


# ------------------------------------------------------------- report build


def collective_totals(ops: list[dict[str, Any]]) -> dict[str, dict[str, Any]]:
    """Aggregate op-site records into per-kind totals: executed count,
    payload bytes and wire bytes across all executions."""
    tot: dict[str, dict[str, Any]] = {}
    for op in ops:
        t = tot.setdefault(op["kind"], {
            "count": 0, "result_bytes": 0, "wire_bytes": 0, "sites": 0,
        })
        t["sites"] += 1
        t["count"] += op["count"]
        t["result_bytes"] += op["result_bytes"] * op["count"]
        t["wire_bytes"] += op["wire_bytes"] * op["count"]
    return tot


def totals_by_axis(ops: list[dict[str, Any]]) -> dict[str, dict[str, Any]]:
    """Per-mesh-axis collective totals (an op over several axes counts
    toward each; axis ``"?"`` collects ops whose groups were unmappable)."""
    out: dict[str, dict[str, Any]] = {}
    for op in ops:
        for ax in (op["axes"] or ["?"]):
            t = out.setdefault(ax, {})
            k = t.setdefault(op["kind"], {"count": 0, "wire_bytes": 0})
            k["count"] += op["count"]
            k["wire_bytes"] += op["wire_bytes"] * op["count"]
    return out


def analyze_compiled(
    compiled: Any,
    mesh=None,
    meta: dict[str, Any] | None = None,
    hlo_text: str | None = None,
) -> dict[str, Any]:
    """Full compile-time report for one compiled XLA program: collective
    inventory (+ per-axis totals), memory footprint, FLOP totals, and
    roofline projections per chip spec.  Works on any backend that can
    compile the program — the intended use is CPU with a fake mesh."""
    from ddl25spring_tpu.utils.compat import (
        compiled_cost_analysis,
        compiled_memory_stats,
    )

    if hlo_text is None:
        hlo_text = compiled.as_text()
    ops = parse_hlo_collectives(hlo_text, mesh)
    aliases = parse_input_output_aliases(hlo_text)
    entry_params = parse_entry_parameters(hlo_text)
    memory = compiled_memory_stats(compiled)
    cost = compiled_cost_analysis(compiled)
    flops = float(cost.get("flops", 0.0)) if cost else None
    bytes_accessed = (
        float(cost.get("bytes accessed", 0.0)) if cost else None
    )
    totals = collective_totals(ops)
    report: dict[str, Any] = {
        "collectives": {
            "ops": ops,
            "totals": totals,
            "by_axis": totals_by_axis(ops),
        },
        "memory": memory,
        # buffer-donation accounting: the bytes the compiled program
        # aliases in place instead of double-buffering (0 = undonated);
        # aliased_params are the entry-parameter numbers the alias table
        # covers (the donation-miss hazard rule diffs these against the
        # donatable inputs — analysis/rules.py H005)
        "donation": {
            "hbm_saved_bytes": (memory or {}).get("alias_size_in_bytes", 0),
            "aliased_params": sorted({a["param_number"] for a in aliases}),
        },
        "entry_params": entry_params,
        "flops": flops if flops and flops > 0 else None,
        "bytes_accessed": bytes_accessed,
        "projection": roofline_projection(
            flops,
            bytes_accessed,
            sum(t["wire_bytes"] for t in totals.values()),
        ),
    }
    if meta:
        report["meta"] = meta
    # whole-program schedule analysis (analysis/sched.py): per-collective
    # overlap-slack windows, participant-stream safety, and the
    # per-strategy static_overlap_bound — computed once here and reused
    # by the lint context, perfscope records, and the report tables;
    # sched breakage degrades to an error note, never costs the report
    try:
        from ddl25spring_tpu.analysis import sched as sched_mod

        report["sched"] = sched_mod.analyze_schedule(
            hlo_text, mesh, ops=ops,
            discipline=sched_mod.discipline_of(meta),
        )
    except Exception as e:  # noqa: BLE001 — degrade per report
        report["sched"] = {"error": f"{type(e).__name__}: {e}"}
    return report


def roofline_projection(
    flops: float | None,
    hbm_bytes: float | None,
    ici_bytes: float,
    chips: list[str] | None = None,
    specs: dict[str, dict[str, float]] | None = None,
) -> dict[str, Any]:
    """Project one step's time/MFU onto real chip specs from the three
    compile-time resource totals: FLOPs (MXU), bytes accessed (HBM), and
    collective wire bytes (ICI).  The projection assumes no overlap — a
    deliberate upper bound on step time; its ``bound`` field names the
    roofline the program would sit on.  ``specs`` overlays/extends
    :data:`~ddl25spring_tpu.utils.flops.CHIP_SPECS` (how perfscope
    injects the runtime-calibrated cpu-host peak, and how
    ``tools/resnet_roofline.py`` derates a peak by MXU occupancy)."""
    from ddl25spring_tpu.utils.flops import CHIP_SPECS

    table: dict[str, dict[str, float]] = dict(CHIP_SPECS)
    if specs:
        table.update(specs)
    out: dict[str, Any] = {}
    if not flops:
        return out
    for kind in (chips or list(table)):
        spec = table.get(kind)
        if not spec:
            continue
        # a peak-only spec (a chip in PEAK_BF16_FLOPS with no full
        # CHIP_SPECS entry, e.g. v2/v3 via host_peak_spec) still
        # projects: an unknown bandwidth simply doesn't bound the step
        t_compute = flops / spec["peak_bf16_flops"]
        hbm_bw = spec.get("hbm_bytes_per_s")
        ici_bw = spec.get("ici_bytes_per_s")
        t_hbm = (hbm_bytes or 0.0) / hbm_bw if hbm_bw else 0.0
        t_ici = ici_bytes / ici_bw if ici_bw else 0.0
        t_step = max(t_compute, t_hbm, t_ici)
        bound = {t_compute: "compute", t_hbm: "hbm", t_ici: "ici"}[t_step]
        out[kind] = {
            "t_compute_s": t_compute,
            "t_hbm_s": t_hbm,
            "t_ici_s": t_ici,
            "projected_step_s": t_step,
            "bound": bound,
            "projected_mfu": t_compute / t_step if t_step > 0 else None,
        }
    return out


# ------------------------------------------------------- signature checking


def check_signature(
    report: dict[str, Any], expected: dict[str, Any]
) -> list[str]:
    """Evaluate a strategy's analytic collective signature against its
    measured compile report.  Returns human-readable violations (empty =
    signature holds).  ``expected`` schema (all keys optional)::

        {
          "forbidden": ["collective-permute", ...],   # kinds that must not appear
          "scalar_bytes": 64,          # per-execution payload <= this is "scalar"
                                       #   noise, exempt from `forbidden`
          "<kind>": {
             "count": 5,               # exact executed count
             "min_count": 1, "max_count": 8,
             "min_bytes": B, "max_bytes": B2,   # total payload bytes
             "axes": ["data"],         # every op of the kind groups only here
          },
          "memory": {                  # peak-HBM budget (memory_analysis)
             "max_peak_hbm_bytes": B,
          },
          "donation": {                # buffer-donation savings: the bytes
             "min_saved_bytes": B,     #   aliased in place of fresh output
          },                           #   buffers (alias_size_in_bytes)
        }
    """
    viols: list[str] = []
    mem = report.get("memory") or {}
    want_mem = expected.get("memory")
    if want_mem and "max_peak_hbm_bytes" in want_mem:
        peak = mem.get("peak_hbm_bytes")
        if peak is None:
            viols.append("memory: no peak-HBM estimate on this backend, "
                         "cannot check the budget")
        elif peak > want_mem["max_peak_hbm_bytes"]:
            viols.append(
                f"memory: peak HBM {peak} B exceeds the "
                f"{want_mem['max_peak_hbm_bytes']} B budget"
            )
    want_don = expected.get("donation")
    if want_don and "min_saved_bytes" in want_don:
        if "alias_size_in_bytes" not in mem:
            # no memory stats != zero bytes donated: report the missing
            # instrument, not a phantom donation regression
            viols.append("donation: no aliasing stats on this backend, "
                         "cannot check the donation floor")
        elif mem["alias_size_in_bytes"] < want_don["min_saved_bytes"]:
            viols.append(
                f"donation: only {mem['alias_size_in_bytes']} B aliased "
                f"in place, expected >= {want_don['min_saved_bytes']} B — "
                "a train step stopped donating its params/opt-state "
                "buffers"
            )
    ops = report["collectives"]["ops"]
    totals = report["collectives"]["totals"]
    scalar = int(expected.get("scalar_bytes", 0))
    for kind in expected.get("forbidden", ()):
        bad = [
            o for o in ops
            if o["kind"] == kind and o["result_bytes"] > scalar
        ]
        if bad:
            viols.append(
                f"forbidden collective {kind}: {len(bad)} op site(s), "
                f"e.g. {bad[0]['result_bytes']} B at {bad[0]['source']}"
            )
    for kind, want in expected.items():
        if kind in ("forbidden", "scalar_bytes", "memory", "donation") or (
            not isinstance(want, dict)
        ):
            continue
        kops = [o for o in ops if o["kind"] == kind]
        count = sum(o["count"] for o in kops)
        tbytes = totals.get(kind, {}).get("result_bytes", 0)
        if "count" in want and count != want["count"]:
            viols.append(f"{kind}: expected exactly {want['count']} "
                         f"executions, measured {count}")
        if "min_count" in want and count < want["min_count"]:
            viols.append(f"{kind}: expected >= {want['min_count']} "
                         f"executions, measured {count}")
        if "max_count" in want and count > want["max_count"]:
            viols.append(f"{kind}: expected <= {want['max_count']} "
                         f"executions, measured {count}")
        if "min_bytes" in want and tbytes < want["min_bytes"]:
            viols.append(f"{kind}: expected >= {want['min_bytes']} total "
                         f"payload bytes, measured {tbytes}")
        if "max_bytes" in want and tbytes > want["max_bytes"]:
            viols.append(f"{kind}: expected <= {want['max_bytes']} total "
                         f"payload bytes, measured {tbytes}")
        if "axes" in want:
            allowed = set(want["axes"])
            for o in kops:
                if o["result_bytes"] <= scalar:
                    continue
                if o["axes"] is not None and not set(o["axes"]) <= allowed:
                    viols.append(
                        f"{kind}: op at {o['source']} groups over "
                        f"{o['axes']}, expected a subset of "
                        f"{sorted(allowed)}"
                    )
    return viols


# -------------------------------------------------------- strategy registry

# name -> (module path, ordered mesh axis names, default mesh sizes).
# Every module's `describe(mesh, **kw)` returns
#   {"fn": lowerable, "args": example inputs, "meta": {...},
#    "expected": signature dict for check_signature}
# — the registry hook the tentpole asks each parallel builder to expose.
STRATEGIES: dict[str, dict[str, Any]] = {
    "dp": {
        "module": "ddl25spring_tpu.parallel.dp",
        "axes": ("data",), "default_mesh": (4,),
    },
    "dp-overlap": {
        # backward-overlapped gradient buckets: each bucket's all-reduce
        # is emitted by a per-bucket custom_vjp bwd rule inside the
        # backward, buckets planned in backward-readiness order
        # (parallel/bucketing.overlap_wrap) — same signature as dp,
        # bitwise-equal params, pinned in tests/test_bucketing.py
        "module": "ddl25spring_tpu.parallel.dp",
        "axes": ("data",), "default_mesh": (4,),
        "kwargs": {"overlap": True},
    },
    "zero1": {
        "module": "ddl25spring_tpu.parallel.zero",
        "axes": ("data",), "default_mesh": (4,), "kwargs": {"stage": 1},
    },
    "zero2": {
        "module": "ddl25spring_tpu.parallel.zero",
        "axes": ("data",), "default_mesh": (4,), "kwargs": {"stage": 2},
    },
    "zero3": {
        "module": "ddl25spring_tpu.parallel.zero",
        "axes": ("data",), "default_mesh": (4,), "kwargs": {"stage": 3},
    },
    "zero3-prefetch": {
        # the scanned-LLaMA double-buffered gather-prefetch step: the
        # layer i+1 all-gather issues before layer i's compute, inside a
        # while loop whose trip count the analytics read off the HLO
        "module": "ddl25spring_tpu.parallel.zero",
        "axes": ("data",), "default_mesh": (4,),
        "kwargs": {"stage": 3, "prefetch": True},
    },
    # backward-overlapped ZeRO variants: the gradient collective (stage
    # 1 all-reduce / stage 2 reduce-scatter / stage 3 bwd reduce-
    # scatter) fires inside the backward per backward-readiness bucket
    "zero1-overlap": {
        "module": "ddl25spring_tpu.parallel.zero",
        "axes": ("data",), "default_mesh": (4,),
        "kwargs": {"stage": 1, "overlap": True},
    },
    "zero2-overlap": {
        "module": "ddl25spring_tpu.parallel.zero",
        "axes": ("data",), "default_mesh": (4,),
        "kwargs": {"stage": 2, "overlap": True},
    },
    "zero3-overlap": {
        "module": "ddl25spring_tpu.parallel.zero",
        "axes": ("data",), "default_mesh": (4,),
        "kwargs": {"stage": 3, "overlap": True},
    },
    "pipeline": {
        "module": "ddl25spring_tpu.parallel.pipeline",
        "axes": ("data", "stage"), "default_mesh": (1, 2),
    },
    "het_pipeline": {
        "module": "ddl25spring_tpu.parallel.het_pipeline",
        "axes": ("data", "stage"), "default_mesh": (1, 2),
    },
    "tp": {
        "module": "ddl25spring_tpu.parallel.tp",
        "axes": ("data", "model"), "default_mesh": (1, 2),
    },
    "sp": {
        "module": "ddl25spring_tpu.parallel.sp",
        "axes": ("data", "seq"), "default_mesh": (1, 2),
    },
    "ep": {
        "module": "ddl25spring_tpu.parallel.ep",
        "axes": ("expert",), "default_mesh": (4,),
    },
    # the serving programs (ddl25spring_tpu/serve/engine.py): TP decode
    # tick and prefill over the paged KV cache — forward-only inference
    # steps whose pinned signature is "row-parallel all-reduce over the
    # model axis ONLY" (no permutes/gathers/scatters: serve keeps the
    # vocab replicated), with HBM budgets like every training strategy
    "serve-decode": {
        "module": "ddl25spring_tpu.serve.engine",
        "axes": ("model",), "default_mesh": (2,),
        "kwargs": {"program": "decode"},
    },
    "serve-prefill": {
        "module": "ddl25spring_tpu.serve.engine",
        "axes": ("model",), "default_mesh": (2,),
        "kwargs": {"program": "prefill"},
    },
    # the radix prefix cache's start-offset prefill variant (PR 11):
    # the scan shortens to max_prompt_len - start positions, so the
    # all-reduce count — and with it the prefill FLOPs a cached prefix
    # skips — is a compile-time fact this signature pin holds
    "serve-prefill-cached": {
        "module": "ddl25spring_tpu.serve.engine",
        "axes": ("model",), "default_mesh": (2,),
        "kwargs": {"program": "prefill", "start": 4},
    },
    # the TP-sharded serving trio (PR 18): the same decode/prefill
    # programs under the tightened per-chip claim — 64 KiB peak-HBM
    # budgets that only hold because the pool's head dim and the
    # Megatron splits divide residency by tp (one chip measures
    # ~83 KiB), all-reduce payloads pinned byte-exact (activation-
    # sized, UNCHANGED by tp) — and the ZeRO-3 weight-streaming decode,
    # whose double-buffered per-layer gather is count-pinned
    # (n_layers x n_buckets) with params/n + one transient layer
    # resident
    "serve-decode-tp": {
        "module": "ddl25spring_tpu.serve.engine",
        "axes": ("model",), "default_mesh": (2,),
        "kwargs": {"program": "decode", "per_chip": True},
    },
    "serve-prefill-tp": {
        "module": "ddl25spring_tpu.serve.engine",
        "axes": ("model",), "default_mesh": (2,),
        "kwargs": {"program": "prefill", "per_chip": True},
    },
    "serve-decode-zero3stream": {
        "module": "ddl25spring_tpu.serve.engine",
        "axes": ("model",), "default_mesh": (2,),
        "kwargs": {"program": "decode", "weight_stream": True},
    },
    # the speculative-decoding pair (PR 13, serve/spec.py): the tiny-
    # LLaMA drafter's k-token proposal scan over its OWN paged pool and
    # the target's single width-(k+1) verify pass — all-reduce-only
    # signatures whose counts differ by exactly the draft/target depth
    # ratio (the compile-time half of the virtual clock's FLOP-ratio
    # pricing), pools head-dim-sharded under the same H013 contract
    "serve-draft": {
        "module": "ddl25spring_tpu.serve.spec",
        "axes": ("model",), "default_mesh": (2,),
        "kwargs": {"program": "draft"},
    },
    "serve-verify": {
        "module": "ddl25spring_tpu.serve.spec",
        "axes": ("model",), "default_mesh": (2,),
        "kwargs": {"program": "verify"},
    },
    # the partition-rule-engine variants (PR 12): the strategy is DATA —
    # a mesh shape + ordered regex rule table + issue discipline
    # (parallel/rules.py) — lowered through the generic RulePartitioner
    # and pinned bitwise-identical to the bespoke dp / zero3 builders
    # (tests/test_shard_flow.py); their tables are proven covered (every
    # param leaf matched exactly once, no shadowed rule) by the
    # sharding-flow verifier's H012 (analysis/shard_flow.py)
    "dp-rules": {
        "module": "ddl25spring_tpu.parallel.rules",
        "axes": ("data",), "default_mesh": (4,),
        "kwargs": {"table": "dp"},
    },
    "zero3-rules": {
        "module": "ddl25spring_tpu.parallel.rules",
        "axes": ("data",), "default_mesh": (4,),
        "kwargs": {"table": "zero3"},
    },
}


def strategy_mesh(name: str, sizes: tuple[int, ...] | None = None):
    """Build the fake CPU mesh a strategy's describe() runs under.
    ``sizes`` maps positionally onto the strategy's axis names; extra
    trailing dims fold into the last axis (so ``zero3 --mesh 2x4`` means
    an 8-way data axis)."""
    import jax

    from ddl25spring_tpu.utils.mesh import make_mesh

    info = STRATEGIES[name]
    axes = info["axes"]
    sizes = tuple(sizes or info["default_mesh"])
    if len(sizes) > len(axes):
        folded = sizes[: len(axes) - 1] + (
            math.prod(sizes[len(axes) - 1:]),
        )
        sizes = folded
    elif len(sizes) < len(axes):
        sizes = (1,) * (len(axes) - len(sizes)) + sizes
    kw = {ax: s for ax, s in zip(axes, sizes) if s > 1}
    if not kw:  # degenerate 1-device request: keep the last axis explicit
        kw = {axes[-1]: sizes[-1]}
    devices = jax.devices("cpu")
    need = math.prod(kw.values())
    if len(devices) < need:
        raise RuntimeError(
            f"strategy {name!r} mesh {kw} needs {need} CPU devices, have "
            f"{len(devices)}; set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need} before "
            "importing jax"
        )
    return make_mesh(devices[:need], **kw)


def describe_strategy(
    name: str, mesh=None, **overrides: Any
) -> dict[str, Any]:
    """Resolve a strategy name to its module's ``describe()`` output."""
    import importlib

    if name not in STRATEGIES:
        raise KeyError(
            f"unknown strategy {name!r}; known: {sorted(STRATEGIES)}"
        )
    info = STRATEGIES[name]
    if mesh is None:
        mesh = strategy_mesh(name)
    mod = importlib.import_module(info["module"])
    kw = dict(info.get("kwargs", {}), **overrides)
    return mod.describe(mesh, **kw)


def compile_strategy(
    name: str,
    mesh_sizes: tuple[int, ...] | None = None,
    lint: bool = True,
    keep_hlo: bool = False,
    **overrides: Any,
) -> dict[str, Any]:
    """Lower + compile one strategy on a fake CPU mesh and analyze it.

    Returns the :func:`analyze_compiled` report extended with
    ``{"strategy", "mesh", "lowered", "expected",
    "signature_violations", "findings"}`` — the last from the static
    hazard analyzer (:mod:`ddl25spring_tpu.analysis`), run over the same
    optimized HLO unless ``lint=False``.  ``keep_hlo=True`` additionally
    stores the optimized-HLO text under ``report["hlo_text"]`` — the
    tests' lower-once cache and ``graft_lint --shard-flow`` opt in so
    the sharding-flow walk and the bitwise rule-table pins reuse the one
    compile; the default stays off so JSON artifacts never carry
    megabytes of HLO.  A strategy whose trace/compile
    fails on this jax (e.g. the homogeneous-pipeline grad path pre-VMA)
    degrades to ``{"strategy", "error"}`` instead of raising — a dead
    strategy must not cost the others' reports.
    """
    try:
        mesh = strategy_mesh(name, mesh_sizes)
        d = describe_strategy(name, mesh, **overrides)
        compiled = d["fn"].lower(*d["args"]).compile()
        hlo_text = compiled.as_text()  # serialized once, analyze + lint
        report = analyze_compiled(
            compiled, mesh, meta=d.get("meta"), hlo_text=hlo_text
        )
    except Exception as e:  # noqa: BLE001 — degrade per strategy
        err: dict[str, Any] = {
            "strategy": name,
            "error": f"{type(e).__name__}: {e}",
        }
        try:
            err["mesh"] = {
                ax: int(s)
                for ax, s in zip(mesh.axis_names, mesh.devices.shape)
            }
        except UnboundLocalError:  # the mesh itself failed to build
            err["mesh_requested"] = list(mesh_sizes or ())
        return err
    report["strategy"] = name
    if keep_hlo:
        report["hlo_text"] = hlo_text
    report["mesh"] = {
        ax: int(s) for ax, s in zip(mesh.axis_names, mesh.devices.shape)
    }
    report["lowered"] = d.get("lowered", "train_step")
    if report["lowered"] == "train_step":
        # which leading entry parameters COULD have been donated: the
        # flattened leaves of (params, opt_state) — donate_argnums=(0, 1)
        # territory.  The donation-miss rule (H005) checks each of these
        # above its byte threshold against the alias table.
        import jax

        report["donation"]["donatable_leaves"] = len(
            jax.tree.leaves(d["args"][:2])
        )
    expected = d.get("expected")
    if expected:
        report["expected"] = expected
        report["signature_violations"] = check_signature(report, expected)
    if lint:
        attach_findings(report, compiled, strategy=name, hlo_text=hlo_text)
    return report


def attach_findings(
    report: dict[str, Any],
    compiled: Any,
    strategy=None,
    hlo_text: str | None = None,
):
    """Run the static hazard analyzer over a compiled program and attach
    its (waiver-resolved) findings to the report as ``report["findings"]``
    (a list of dicts).  Pass ``hlo_text`` when the module text is already
    in hand (``compiled.as_text()`` re-serializes the whole program).
    Lint breakage degrades to ``report["lint_error"]`` — the analytics
    must never cost the report itself."""
    try:
        from ddl25spring_tpu.analysis import engine

        report["findings"] = [
            f.to_dict()
            for f in engine.lint_hlo_text(
                hlo_text if hlo_text is not None else compiled.as_text(),
                report=report,
                strategy=strategy,
            )
        ]
    except Exception as e:  # noqa: BLE001 — degrade, keep the report
        report["lint_error"] = f"{type(e).__name__}: {e}"
    return report
