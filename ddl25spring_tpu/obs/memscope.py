"""graft-mem: the runtime memory & resource observatory (PR 17).

The stack pins peak HBM *at compile time* (PR-2 ``memory_analysis()``
budgets, PR-3 donation floors) and narrates *events* at runtime (PR-16
timeline) — but nothing watched runtime memory itself.  A serving fleet
lives and dies by KV page-pool occupancy, fragmentation, and slow leaks
that only show up over thousands of requests; this module closes the
loop by MEASURING what the accounting promised:

- :func:`live_array_summary` / :func:`live_total_bytes` — walk
  ``jax.live_arrays()`` and aggregate by sharding class (count, bytes,
  top-N largest with shape/dtype/sharding).  The flight recorder folds
  the summary into every dump, so an OOM-shaped death is diagnosable
  from ``flight.json`` alone.
- :func:`host_rss_bytes` — resident set size from ``/proc/self/statm``
  (None off Linux): the host-side leak axis (a growing Python list
  never shows in ``live_arrays``).
- :class:`MemScope` — the per-loop sampler: capped reservoirs
  (:class:`Series`) of live bytes / RSS on a step or tick cadence,
  exact high-water marks, timeline ``mem_sample`` mirrors, and a
  windowed monotone-growth detector (:class:`GrowthDetector`) that
  fires a flight ``kind="mem"`` violation naming the growing resource.
- :func:`pool_snapshot` / :func:`pool_leak_check` — KV page-pool
  introspection (occupancy, cache-held vs table-held split, refcount
  histogram, free-run fragmentation) and the drain-time leak detector:
  an idle pool must hold EXACTLY its cache-held pages; any residue is
  attributed (table row -> rid when possible) and fails
  ``tools/mem_report.py --check``.
- :func:`mem_record` / :func:`write_run_mem` — the ``record:"mem"``
  envelope (keyed strategy/mesh/host like the perf rows) appended to
  ``runs/perf_ledger.jsonl`` and written to ``<run_dir>/mem.json`` for
  ``obs_report``'s Memory section and the ``mem_report`` gates.

**Budget-vs-measured semantics** (the gate ``mem_report --check``
enforces): ``budget_bytes`` is the accounted persistent footprint — for
serve, the exact static bill of params + page pools
(:meth:`ServeEngine.mem_budget_bytes`); for training, the live-bytes
baseline captured right after build (params + opt state + data
resident).  The runtime high-water ``live_bytes_peak`` must sit within
``budget_bytes * (1 + tolerance)``; where a registered strategy
additionally declares a compile-time ``memory.max_peak_hbm_bytes``
budget (:func:`describe_budget_bytes`), that rides the record for the
trend report.  Everything here is host-side observation: with
``DDL25_MEMSCOPE=0`` (or obs off) no sample is taken and compiled
programs are byte-identical — pinned in ``tests/test_memscope.py``.
"""

from __future__ import annotations

import contextlib
import json
import os
import random
import time
from collections import deque
from typing import Any, Callable, Iterable

from ddl25spring_tpu.obs import state
from ddl25spring_tpu.utils.config import env_flag, env_float

MEM_BASENAME = "mem.json"
SERIES_CAP = 512

#: default budget band: measured high-water live bytes may exceed the
#: accounted budget by this fraction before the gate fails (runtime
#: live arrays include jax-internal constants/donation scratch the
#: static bill does not enumerate)
DEFAULT_TOLERANCE = 0.5

#: sampler gate — ``DDL25_MEMSCOPE=0`` turns every sampler into a no-op
#: even when obs is on (the HLO/bitwise pins toggle this, not DDL25_OBS)
_flag_enabled = env_flag("DDL25_MEMSCOPE", True)


def enabled() -> bool:
    """True when memory sampling is on: obs enabled AND the
    ``DDL25_MEMSCOPE`` flag not zeroed."""
    return _flag_enabled and state.enabled()


def set_flag(on: bool) -> None:
    global _flag_enabled
    _flag_enabled = bool(on)


@contextlib.contextmanager
def scoped(on: bool):
    """Temporarily force the memscope flag (tests; composes with
    ``obs.state.scoped``)."""
    global _flag_enabled
    prev = _flag_enabled
    _flag_enabled = bool(on)
    try:
        yield
    finally:
        _flag_enabled = prev


def tolerance() -> float:
    """The budget band width (``DDL25_MEM_TOL`` overrides)."""
    return env_float("DDL25_MEM_TOL", DEFAULT_TOLERANCE)


# ------------------------------------------------------------- host side


def host_rss_bytes() -> int | None:
    """Resident set size of this process from ``/proc/self/statm``
    (field 2, in pages) — None where procfs is unavailable."""
    try:
        with open("/proc/self/statm") as f:
            fields = f.read().split()
        return int(fields[1]) * (os.sysconf("SC_PAGE_SIZE") or 4096)
    except (OSError, IndexError, ValueError):
        return None


# ----------------------------------------------------------- device side


def _array_nbytes(a) -> int:
    try:
        return int(a.size) * int(a.dtype.itemsize)
    except Exception:  # noqa: BLE001 — a half-deleted array must not kill
        return 0


def _sharding_key(a) -> str:
    """Aggregation key: sharding class + device platform + device count
    — 'SingleDeviceSharding/cpu x1', 'NamedSharding/tpu x8', ...  The
    strategy-level grouping the summary buckets live bytes by."""
    try:
        sh = a.sharding
        n = len(sh.device_set)
        platform = next(iter(sh.device_set)).platform
        return f"{type(sh).__name__}/{platform} x{n}"
    except Exception:  # noqa: BLE001
        return "unknown"


def live_arrays() -> list:
    """Non-deleted ``jax.live_arrays()``, empty when jax is unusable
    (a crash dump must never raise from here)."""
    try:
        import jax

        return [
            a for a in jax.live_arrays()
            if not getattr(a, "is_deleted", lambda: False)()
        ]
    except Exception:  # noqa: BLE001
        return []


def live_total_bytes() -> int:
    """Total committed bytes across every live jax array — the fast
    per-sample aggregate (no per-array dict building)."""
    return sum(_array_nbytes(a) for a in live_arrays())


def live_array_summary(top: int = 10) -> dict[str, Any]:
    """The full live-array picture: count, total bytes, per-sharding
    buckets, and the ``top`` largest arrays with shape/dtype/sharding —
    what the flight recorder folds into every dump (satellite: an
    OOM-shaped death names its offenders from ``flight.json`` alone)."""
    arrs = live_arrays()
    by_sharding: dict[str, dict[str, int]] = {}
    sized = []
    total = 0
    for a in arrs:
        nb = _array_nbytes(a)
        total += nb
        key = _sharding_key(a)
        b = by_sharding.setdefault(key, {"count": 0, "bytes": 0})
        b["count"] += 1
        b["bytes"] += nb
        sized.append((nb, a))
    sized.sort(key=lambda t: -t[0])
    largest = []
    for nb, a in sized[:top]:
        try:
            largest.append({
                "shape": list(a.shape),
                "dtype": str(a.dtype),
                "bytes": nb,
                "sharding": _sharding_key(a),
            })
        except Exception:  # noqa: BLE001
            largest.append({"bytes": nb, "error": "unreadable"})
    return {
        "count": len(arrs),
        "total_bytes": total,
        "by_sharding": by_sharding,
        "largest": largest,
    }


# ------------------------------------------------------- bounded series


class Series:
    """Algorithm-R reservoir + exact count/max/min/total over the full
    stream — the same bounded-host-series contract as the serve
    engine's ``Reservoir`` (kept local: obs/ must not import serve/).
    Below ``cap`` it is exactly an insertion-ordered list."""

    __slots__ = ("cap", "count", "max", "min", "total", "_xs", "_rng",
                 "_seed")

    def __init__(self, cap: int = SERIES_CAP, seed: int = 0):
        self.cap = int(cap)
        self._seed = int(seed)
        self._xs: list = []
        self._rng = random.Random(self._seed)
        self.count = 0
        self.max: float | None = None
        self.min: float | None = None
        self.total = 0.0

    def append(self, x) -> None:
        self.count += 1
        if isinstance(x, (int, float)) and not isinstance(x, bool):
            self.total += x
            if self.max is None or x > self.max:
                self.max = x
            if self.min is None or x < self.min:
                self.min = x
        if len(self._xs) < self.cap:
            self._xs.append(x)
        else:
            j = self._rng.randrange(self.count)
            if j < self.cap:
                self._xs[j] = x

    def clear(self) -> None:
        self._xs.clear()
        self._rng = random.Random(self._seed)
        self.count = 0
        self.max = None
        self.min = None
        self.total = 0.0

    def __iter__(self):
        return iter(self._xs)

    def __len__(self) -> int:
        return len(self._xs)

    def summary(self) -> dict[str, Any]:
        return {
            "count": self.count,
            "sampled": len(self._xs),
            "cap": self.cap,
            "max": self.max,
            "min": self.min,
            "mean": (
                round(self.total / self.count, 3) if self.count else None
            ),
        }


# ------------------------------------------------- monotone-growth leak


class GrowthDetector:
    """Windowed monotone-growth detector for host-side resources.

    A watched series that rises on EVERY observation across a full
    window of ``window`` samples, by at least ``min_growth_bytes``
    total, is a leak-shaped signal — fired ONCE per source (latched),
    as a dict naming the offender.  A series that plateaus or dips
    anywhere inside the window stays quiet (the near-miss negative the
    tests pin), as does growth below the byte floor (allocator noise)."""

    def __init__(self, window: int = 8,
                 min_growth_bytes: int = 1 << 20):
        if window < 2:
            raise ValueError(f"window={window} must be >= 2")
        self.window = int(window)
        self.min_growth_bytes = int(min_growth_bytes)
        self._hist: dict[str, deque] = {}
        self.fired: dict[str, dict[str, Any]] = {}

    def observe(self, source: str, value: float,
                step: int | None = None) -> dict[str, Any] | None:
        """Feed one sample; returns the violation dict the first time
        ``source`` completes a strictly-increasing window, else None."""
        h = self._hist.setdefault(source, deque(maxlen=self.window))
        h.append(float(value))
        if source in self.fired or len(h) < self.window:
            return None
        xs = list(h)
        monotone = all(b > a for a, b in zip(xs, xs[1:]))
        growth = xs[-1] - xs[0]
        if not monotone or growth < self.min_growth_bytes:
            return None
        v = {
            "kind": "mem",
            "source": source,
            "growth_bytes": int(growth),
            "window": self.window,
            "first_bytes": int(xs[0]),
            "last_bytes": int(xs[-1]),
            **({"step": int(step)} if step is not None else {}),
        }
        self.fired[source] = v
        return v


# ------------------------------------------------------------ the scope


class MemScope:
    """One loop's memory sampler: bounded series of live bytes / host
    RSS, exact high-water marks, watched host resources through a
    :class:`GrowthDetector`, and timeline ``mem_sample`` mirrors.

    Construction is always cheap; :meth:`sample` is a no-op unless
    :func:`enabled` — so wiring a scope through a loop costs nothing
    when memory observation is off (the disabled-identical pin).
    ``every`` thins the cadence (sample 1 tick in N)."""

    def __init__(self, label: str = "train", *, every: int = 1,
                 cap: int = SERIES_CAP, window: int = 8,
                 min_growth_bytes: int = 1 << 20):
        self.label = label
        self.every = max(1, int(every))
        self.live_bytes = Series(cap)
        self.rss_bytes = Series(cap)
        self.live_bytes_peak = 0
        self.rss_bytes_peak = 0
        self.live_bytes_baseline: int | None = None
        self.detector = GrowthDetector(
            window=window, min_growth_bytes=min_growth_bytes
        )
        self.violations: list[dict[str, Any]] = []
        self._watches: dict[str, Callable[[], float]] = {}
        self._n = 0

    # -- configuration ------------------------------------------------

    def watch(self, name: str, fn: Callable[[], float]) -> None:
        """Register a host resource (callable -> byte count) for the
        monotone-growth detector; ``host_rss`` is always watched."""
        self._watches[name] = fn

    def set_baseline(self) -> int | None:
        """Capture the persistent live-bytes floor (call once, after
        build / warmup): the budget anchor the training gate bands."""
        if not enabled():
            return None
        self.live_bytes_baseline = live_total_bytes()
        return self.live_bytes_baseline

    def reset(self) -> None:
        """Forget everything (the serve engine's warmup reset)."""
        self.live_bytes.clear()
        self.rss_bytes.clear()
        self.live_bytes_peak = 0
        self.rss_bytes_peak = 0
        self.live_bytes_baseline = None
        self.detector = GrowthDetector(
            window=self.detector.window,
            min_growth_bytes=self.detector.min_growth_bytes,
        )
        self.violations = []
        self._n = 0

    # -- sampling -----------------------------------------------------

    def sample(self, step: int | None = None, *,
               vt: float | None = None, engine: str | None = None,
               replica: int | None = None,
               **extra: Any) -> dict[str, Any] | None:
        """Take one sample (thinned to 1-in-``every``): live bytes +
        RSS into the series and peaks, watched resources through the
        growth detector (violations -> flight ``kind="mem"``), and a
        timeline ``mem_sample`` event carrying ``extra`` (pool
        occupancy, queue depth, tokens/sec — the counter-track
        payload).  Returns the sample dict, or None when off-cadence
        or disabled."""
        if not enabled():
            return None
        self._n += 1
        if (self._n - 1) % self.every:
            return None
        live = live_total_bytes()
        rss = host_rss_bytes()
        if self.live_bytes_baseline is None:
            # the first sample IS the training baseline: it sees the
            # steady-state placement (e.g. DP replication materializes
            # on the first dispatch), which a post-build probe
            # undercounts by the replication factor
            self.live_bytes_baseline = live
        self.live_bytes.append(live)
        self.live_bytes_peak = max(self.live_bytes_peak, live)
        if rss is not None:
            self.rss_bytes.append(rss)
            self.rss_bytes_peak = max(self.rss_bytes_peak, rss)
        for name, fn in [
            ("host_rss", lambda: rss if rss is not None else 0.0),
            *self._watches.items(),
        ]:
            try:
                value = float(fn())
            except Exception:  # noqa: BLE001 — a probe must not kill
                continue
            v = self.detector.observe(name, value, step)
            if v is not None:
                v["scope"] = self.label
                self.violations.append(v)
                from ddl25spring_tpu.obs.recorder import flight

                flight.record(**v)
        sample = {
            "live_bytes": live,
            **({"rss_bytes": rss} if rss is not None else {}),
            **({"step": step} if step is not None else {}),
            **extra,
        }
        from ddl25spring_tpu.obs.timeline import timeline

        timeline.emit(
            "mem_sample", vt=vt, engine=engine or self.label,
            replica=replica, **sample,
        )
        return sample

    # -- folding ------------------------------------------------------

    def cell(self) -> dict[str, Any]:
        """The scope's summary cell (rides ``telemetry.mem`` and the
        mem record)."""
        return {
            "label": self.label,
            "samples": self.live_bytes.count,
            "every": self.every,
            "live_bytes_peak": self.live_bytes_peak,
            "rss_bytes_peak": self.rss_bytes_peak,
            "live_bytes_baseline": self.live_bytes_baseline,
            "live_bytes": self.live_bytes.summary(),
            "rss_bytes": self.rss_bytes.summary(),
            "growth_violations": list(self.violations),
        }


# -------------------------------------------------- KV page-pool optics


def _free_runs(free: Iterable[bool]) -> list[int]:
    runs: list[int] = []
    n = 0
    for f in free:
        if f:
            n += 1
        elif n:
            runs.append(n)
            n = 0
    if n:
        runs.append(n)
    return runs


def pool_snapshot(pool: dict[str, Any],
                  cache_held: int = 0) -> dict[str, Any]:
    """Host-side KV pool telemetry from the device ``free`` /
    ``refcount`` masks (tiny transfers — ``n_pages`` bools/int32s):
    occupancy, the cache-held vs table-held split, a refcount
    histogram, and the free-run fragmentation metric.

    ``fragmentation`` is ``1 - largest_free_run / free_pages`` (0 = one
    contiguous free region, -> 1 = free pages shattered into single
    slots).  The pool allocates page-at-a-time, so fragmentation never
    blocks an allocation here — the metric exists because real engines
    with multi-page contiguous needs die on exactly this curve."""
    import numpy as np

    free = np.asarray(pool["free"]).astype(bool)
    ref = np.asarray(pool["refcount"]).astype(int)
    n_pages = int(free.shape[0])
    used = int((~free).sum())
    free_n = n_pages - used
    runs = _free_runs(free.tolist())
    vals, counts = np.unique(ref[ref > 0], return_counts=True)
    return {
        "n_pages": n_pages,
        "used_pages": used,
        "free_pages": free_n,
        "occupancy": round(used / n_pages, 4) if n_pages else 0.0,
        "cache_held_pages": int(cache_held),
        "table_held_pages": max(used - int(cache_held), 0),
        "refcount_hist": {
            str(int(v)): int(c) for v, c in zip(vals, counts)
        },
        "free_runs": {
            "count": len(runs),
            "max": max(runs) if runs else 0,
            "mean": round(sum(runs) / len(runs), 2) if runs else 0.0,
        },
        "fragmentation": (
            round(1.0 - max(runs) / free_n, 4) if free_n else 0.0
        ),
    }


def pool_leak_check(
    pool: dict[str, Any],
    *,
    cache_held_pages: int = 0,
    slot_rids: list | None = None,
) -> dict[str, Any]:
    """The drain-time leak detector: an idle pool must hold EXACTLY its
    cache-held pages.  Any residue is enumerated page by page and
    attributed — a page still seated in a page-table row is named by
    that row's last rid (``slot_rids``); a page referenced by nothing
    we can see is an orphan (a lost external reference).  ``ok=False``
    fails ``mem_report --check``."""
    import numpy as np

    free = np.asarray(pool["free"]).astype(bool)
    ref = np.asarray(pool["refcount"]).astype(int)
    table = np.asarray(pool["page_table"]).astype(int)
    used = int((~free).sum())
    residue = used - int(cache_held_pages)
    out: dict[str, Any] = {
        "ok": residue <= 0,
        "used_pages": used,
        "cache_held_pages": int(cache_held_pages),
        "leaked_pages": max(residue, 0),
        "leaks": [],
    }
    if residue <= 0:
        return out
    # page -> the table row(s) still holding it; at drain every row
    # should be -1, so any hit is the leak's name
    holders: dict[int, list[int]] = {}
    for slot in range(table.shape[0]):
        for page in table[slot]:
            if page >= 0:
                holders.setdefault(int(page), []).append(slot)
    leaks = []
    for page in np.nonzero(~free)[0]:
        page = int(page)
        rows = holders.get(page)
        if rows is not None:
            for slot in rows:
                rid = (
                    slot_rids[slot]
                    if slot_rids is not None and slot < len(slot_rids)
                    else None
                )
                leaks.append({
                    "page": page,
                    "refcount": int(ref[page]),
                    "held_by": "page_table",
                    "slot": slot,
                    **({"rid": rid} if rid is not None else {}),
                })
        else:
            leaks.append({
                "page": page,
                "refcount": int(ref[page]),
                "held_by": "orphan_refcount",
            })
    # cache-held pages legitimately sit outside any table; keep only
    # the residue count of orphans beyond what the cache accounts for
    orphans = [x for x in leaks if x["held_by"] == "orphan_refcount"]
    tabled = [x for x in leaks if x["held_by"] == "page_table"]
    excess_orphans = orphans[
        : max(len(orphans) - int(cache_held_pages), 0)
    ]
    out["leaks"] = tabled + excess_orphans
    return out


# --------------------------------------------------------- the envelope


def describe_budget_bytes(strategy: str) -> int | None:
    """The compile-time peak-HBM budget a registered strategy declares
    (``describe()['expected']['memory']['max_peak_hbm_bytes']``) —
    None for workloads outside the registry (the bench resnet, serve
    models): those gate on the static accounting instead."""
    try:
        from ddl25spring_tpu.obs import xla_analytics as xa

        if strategy not in getattr(xa, "STRATEGIES", {}):
            return None
        d = xa.describe_strategy(strategy)
        b = (d.get("expected") or {}).get("memory", {}).get(
            "max_peak_hbm_bytes"
        )
        return int(b) if b is not None else None
    except Exception:  # noqa: BLE001 — budget lookup is best-effort
        return None


def budget_cell(
    measured_peak_bytes: int,
    budget_bytes: int | None,
    *,
    tol: float | None = None,
    source: str = "static_accounting",
) -> dict[str, Any]:
    """The budget-vs-measured verdict: ``within_band`` iff the runtime
    high-water sits at or under ``budget_bytes * (1 + tol)``."""
    tol = tolerance() if tol is None else tol
    if not budget_bytes:
        return {"available": False, "source": source}
    ratio = measured_peak_bytes / budget_bytes
    return {
        "available": True,
        "source": source,
        "budget_bytes": int(budget_bytes),
        "measured_peak_bytes": int(measured_peak_bytes),
        "ratio": round(ratio, 4),
        "tolerance": tol,
        "within_band": ratio <= 1.0 + tol,
    }


def mem_record(
    *,
    strategy: str,
    mesh: dict[str, int] | None,
    scope_cell: dict[str, Any],
    budget: dict[str, Any],
    pool: dict[str, Any] | None = None,
    leaks: list[dict[str, Any]] | None = None,
    reshape_steps: list[dict[str, Any]] | None = None,
    extra: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """One ``record:"mem"`` ledger row / ``mem.json`` document — same
    identity envelope as the perf rows (strategy/mesh/host/git_sha), so
    ``mem_report`` groups trends the same way ``perf_report`` does."""
    import jax

    from ddl25spring_tpu.obs.logger import git_sha
    from ddl25spring_tpu.obs.perfscope import host_fingerprint

    return {
        "record": "mem",
        "schema": 1,
        "ts": time.time(),
        "strategy": strategy,
        "mesh": dict(mesh or {}),
        "host": host_fingerprint(),
        "git_sha": git_sha(),
        "jax_version": jax.__version__,
        "memscope": scope_cell,
        "budget": budget,
        **({"pool": pool} if pool is not None else {}),
        "leaks": list(leaks or []),
        "leaked_pages": sum(
            x.get("leaked_pages", 0) for x in (leaks or [])
        ),
        "growth_violations": len(
            scope_cell.get("growth_violations") or []
        ),
        **({"reshape_steps": reshape_steps}
           if reshape_steps is not None else {}),
        **(extra or {}),
    }


def mem_cell(record: dict[str, Any]) -> dict[str, Any]:
    """The ``telemetry.mem`` BENCH cell — the contract keys the CI
    smoke asserts (peaks, budget verdict, leak + growth counters),
    folded from one :func:`mem_record`."""
    scope = record.get("memscope") or {}
    cell: dict[str, Any] = {
        "enabled": True,
        "samples": scope.get("samples"),
        "live_bytes_peak": scope.get("live_bytes_peak"),
        "rss_bytes_peak": scope.get("rss_bytes_peak"),
        "budget": record.get("budget"),
        "leaked_pages": record.get("leaked_pages", 0),
        "growth_violations": record.get("growth_violations", 0),
    }
    pool = record.get("pool")
    if pool is not None:
        cell["pool"] = {
            k: pool.get(k)
            for k in ("n_pages", "used_pages", "occupancy",
                      "cache_held_pages", "table_held_pages",
                      "fragmentation")
        }
    steps = record.get("reshape_steps")
    if steps:
        cell["reshape_steps"] = len(steps)
        cell["reshape_step_down_bytes"] = sum(
            s.get("step_down_bytes", 0) for s in steps
        )
    return cell


def write_run_mem(record: dict[str, Any], run_dir: str) -> str:
    """``<run_dir>/mem.json``, atomically (temp + rename, the
    write_run_perf pattern) — what ``obs_report``'s Memory section and
    ``mem_report --run`` read."""
    os.makedirs(run_dir, exist_ok=True)
    path = os.path.join(run_dir, MEM_BASENAME)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1, default=str)
    os.replace(tmp, path)
    return path
