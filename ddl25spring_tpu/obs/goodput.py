"""graft-goodput: run-lineage goodput & SLO decomposition (PR 20).

The production top-line metric is **goodput**: the fraction of
wall-clock chip time spent doing useful, SLO-compliant work — not step
time, not MFU alone.  The repo records every ingredient (timeline
events, flight counters, reshape windows, perf ledger, serve TTFT
decomposition); this module folds them into ONE number plus the honest
decomposition behind it:

- **Run lineage.**  ``bench.py``'s retry parent mints a ``lineage_id``
  (:func:`mint_lineage_id`) and a per-attempt index, propagated to every
  child through the sanctioned env boundary (``DDL25_LINEAGE`` /
  ``DDL25_ATTEMPT``), stamped into the child's timeline header, flight
  meta, and each per-attempt retry JSONL record.  A resumed child
  carries the SAME lineage_id — the lineage is the unit a production
  goodput number is quoted over, because each attempt's own artifacts
  (flight.json, metrics.jsonl) are overwritten by the next one.

- **Badput taxonomy.**  :class:`GoodputMeter` decomposes one attempt's
  wall into typed buckets (:data:`BUCKETS`) from *measured* windows:
  ``useful_step`` (timed dispatch walls), ``warmup_compile`` (the
  bracketed warmup/compile phase of every ``timed_run`` call),
  ``checkpoint_save`` (host-blocking autosave enqueue walls),
  ``replayed_steps`` (durable-gap steps re-run after a resume — the
  same dispatch walls, re-bucketed by global step index), ``stall``
  (watchdog idle windows, seconds only — a stall that later completes
  would overlap its step window, so stalls never emit windows),
  ``recovery`` (process entry -> restored on a relaunch; retry backoff
  and a dead attempt's lost tail on the lineage view),
  ``reshape_window`` (the elastic in-process mesh reshapes).  The
  residual is ``other`` — reported, never silently dropped — and the
  attributed sum may exceed total wall by at most
  :data:`SUM_TOLERANCE` (float re-association across clocks), a pinned
  contract ``tests/test_goodput.py`` and ``trace_export --check``
  enforce.

- **Lineage merge.**  :func:`merge_lineage` folds every attempt of a
  lineage onto one wall-clock axis: the final attempt contributes its
  full decomposition; each FAILED attempt contributes the durable-step
  walls its flight dump vouches for as ``useful_step``, its lost tail
  (steps past the durable checkpoint — work the resume re-pays) plus
  the retry backoff as ``recovery``, and its unattributed setup as
  ``other``.

- **Serving goodput.**  :func:`serve_goodput_cell` prices SLO
  attainment per completed request (TTFT + per-token latency against
  ``DDL25_SLO_TTFT_MS`` / ``DDL25_SLO_TOK_MS``, denominated in the
  ENGINE clock — virtual on deterministic arms, where wall is
  noise-bound), goodput tokens/sec/chip counting SLO-compliant
  completed tokens only, and availability =
  ``1 - (rejects + drops + drain-window demand) / offered``.

Artifacts: a per-run ``goodput.json`` (:func:`write_run_goodput`), a
``telemetry.goodput`` cell on BENCH lines, and ``record: "goodput"``
ledger rows (:func:`ledger_row`) keyed (strategy, mesh, host, scope)
with the lineage id riding as identity — gated by
``tools/goodput_report.py --check``.

Everything here is host-side stdlib bookkeeping: no jax import, never
part of a compiled program, and a run with obs off simply never calls
it — compiled HLO and serve token streams stay bitwise identical
(pinned in ``tests/test_goodput.py``).
"""

from __future__ import annotations

import json
import os
import time
import uuid

GOODPUT_BASENAME = "goodput.json"

#: decomposition buckets, in render order.  ``other`` is the residual
#: (total wall minus everything measured) — reported, never dropped.
BUCKETS = (
    "useful_step",
    "warmup_compile",
    "checkpoint_save",
    "replayed_steps",
    "stall",
    "recovery",
    "reshape_window",
    "other",
)

#: pinned tolerance: the measured (attributed) seconds may exceed the
#: total wall by at most this fraction — the buckets come from
#: independent perf_counter brackets, so float re-association earns a
#: hair of slack, and anything beyond it is a double-billed window.
SUM_TOLERANCE = 0.02

#: the sanctioned env boundary for lineage propagation (retry parent ->
#: child) and serving SLOs.  Read via utils.config helpers only.
ENV_LINEAGE = "DDL25_LINEAGE"
ENV_ATTEMPT = "DDL25_ATTEMPT"
ENV_SLO_TTFT_MS = "DDL25_SLO_TTFT_MS"
ENV_SLO_TOK_MS = "DDL25_SLO_TOK_MS"

#: window-list bound: a soak run's per-step windows must not grow
#: goodput.json without limit — past the cap, seconds still accumulate
#: (the decomposition stays exact) and the doc says it truncated.
MAX_WINDOWS = 4096

# CI-smoke SLO defaults: generous enough that a healthy tiny-model CPU
# smoke attains them (the ramp runs on the WALL clock of a loaded CI
# box), tight enough that a wedged engine misses.  Operators override
# through the env boundary.
DEFAULT_SLO_TTFT_MS = 2000.0
DEFAULT_SLO_TOK_MS = 500.0


def mint_lineage_id() -> str:
    """A fresh lineage id (12 hex chars — unique per retry lineage,
    short enough to read in a ledger row)."""
    return uuid.uuid4().hex[:12]


def lineage_from_env() -> tuple[str | None, int]:
    """``(lineage_id, attempt)`` from the sanctioned env boundary —
    ``(None, 1)`` when no retry parent minted one (an in-process run
    mints its own)."""
    from ddl25spring_tpu.utils.config import env_int, env_str

    return env_str(ENV_LINEAGE), max(1, env_int(ENV_ATTEMPT, 1))


def serve_slo() -> dict:
    """The serving SLO thresholds, env boundary over smoke defaults."""
    from ddl25spring_tpu.utils.config import env_float

    return {
        "ttft_ms": env_float(ENV_SLO_TTFT_MS, DEFAULT_SLO_TTFT_MS),
        "tok_ms": env_float(ENV_SLO_TOK_MS, DEFAULT_SLO_TOK_MS),
    }


# ------------------------------------------------------------------ meter


class GoodputMeter:
    """Per-attempt wall-clock decomposition accumulator.

    One meter per process, anchored at the driver's entry perf-counter
    (``t0_perf``) so ``recovery`` can bill process entry -> restored.
    Buckets accumulate through :meth:`add` (measured ``[t0, t1)``
    windows on the meter's own axis, disjoint by construction at every
    call site) and :meth:`add_seconds` (duration-only facts like
    watchdog idle time whose window would overlap a step's).
    :meth:`finalize` closes the attempt: the residual becomes
    ``other`` and the sum contract is self-checked.
    """

    def __init__(
        self,
        lineage_id: str,
        attempt: int = 1,
        *,
        t0_perf: float | None = None,
        chips: int = 1,
    ):
        self.lineage_id = lineage_id
        self.attempt = int(attempt)
        self._t0 = time.perf_counter() if t0_perf is None else t0_perf
        # unix anchor for the SAME instant as _t0, so lineage merging
        # and the trace exporter can shift windows across attempts
        self.t0_unix = time.time() - (time.perf_counter() - self._t0)
        self.chips = max(1, int(chips))
        self.seconds: dict[str, float] = {}
        self.chip_seconds: dict[str, float] = {}
        self.windows: list[dict] = []
        self.windows_truncated = 0
        self.step_counts: dict[str, int] = {}
        # global step indices a resumed attempt re-runs (the durable
        # gap): timed dispatches landing on them bill replayed_steps
        self.replay_steps: frozenset[int] = frozenset()

    def now(self) -> float:
        """Seconds since the meter origin (the decomposition axis)."""
        return time.perf_counter() - self._t0

    def set_replay_window(self, start_step: int, last_prev_step: int) -> None:
        """Declare the durable gap ``[start_step, last_prev_step]`` —
        the steps a resumed attempt re-runs.  Their count must equal
        the manifest durable gap exactly (pinned)."""
        self.replay_steps = frozenset(
            range(int(start_step), int(last_prev_step) + 1)
        )

    def add_seconds(self, bucket: str, seconds: float,
                    *, chips: int | None = None) -> None:
        """Accumulate a duration with no window (stalls: the idle time
        is real, but its span overlaps the step that eventually
        completed — emitting it as a window would break the
        no-overlap contract)."""
        if bucket not in BUCKETS:
            raise ValueError(f"unknown goodput bucket {bucket!r}")
        s = max(0.0, float(seconds))
        c = self.chips if chips is None else max(1, int(chips))
        self.seconds[bucket] = self.seconds.get(bucket, 0.0) + s
        self.chip_seconds[bucket] = (
            self.chip_seconds.get(bucket, 0.0) + s * c
        )

    def add(self, bucket: str, t0_s: float, t1_s: float,
            *, chips: int | None = None, **facts) -> None:
        """Accumulate one measured window ``[t0_s, t1_s)`` on the meter
        axis.  Call sites keep windows disjoint by construction; the
        exporter's ``--check`` refuses overlap after the fact."""
        if bucket not in BUCKETS:
            raise ValueError(f"unknown goodput bucket {bucket!r}")
        t0_s, t1_s = float(t0_s), float(t1_s)
        if t1_s < t0_s:
            t0_s, t1_s = t1_s, t0_s
        self.add_seconds(bucket, t1_s - t0_s, chips=chips)
        if len(self.windows) >= MAX_WINDOWS:
            self.windows_truncated += 1
            return
        self.windows.append({
            "bucket": bucket,
            "t0_s": round(t0_s, 6),
            "t1_s": round(t1_s, 6),
            **({"chips": chips} if chips is not None else {}),
            **facts,
        })

    def note_step(self, global_step: int, t0_s: float, t1_s: float,
                  *, chips: int | None = None,
                  resumable: bool = True) -> None:
        """One timed dispatch window, bucketed ``useful_step`` or
        ``replayed_steps`` by its GLOBAL step index (the durable-gap
        re-runs are the same walls, differently billed).  Only a
        ``resumable`` phase's indices share units with the durable
        steps (the flight-record marker): a secondary phase restarting
        its own count at 0 must not collide with the replay window."""
        bucket = (
            "replayed_steps"
            if resumable and global_step in self.replay_steps
            else "useful_step"
        )
        self.step_counts[bucket] = self.step_counts.get(bucket, 0) + 1
        self.add(bucket, t0_s, t1_s, chips=chips, step=int(global_step))

    # ---- closing the attempt -------------------------------------------

    def _coalesced_windows(self) -> list[dict]:
        """Merge touching same-bucket windows (per-step windows of one
        phase collapse to one span) so goodput.json stays readable."""
        out: list[dict] = []
        for w in sorted(self.windows, key=lambda w: (w["t0_s"], w["t1_s"])):
            if (
                out
                and out[-1]["bucket"] == w["bucket"]
                and out[-1].get("chips") == w.get("chips")
                and w["t0_s"] - out[-1]["t1_s"] <= 1e-4
            ):
                out[-1] = {
                    **out[-1],
                    "t1_s": max(out[-1]["t1_s"], w["t1_s"]),
                    "n": out[-1].get("n", 1) + 1,
                }
            else:
                out.append(dict(w))
        return out

    def finalize(self, total_wall_s: float | None = None,
                 *, scope: str = "train_attempt", **extra) -> dict:
        """Close the decomposition: residual -> ``other``, sum contract
        self-checked, windows coalesced.  Returns the goodput doc
        (what ``goodput.json`` holds and ``telemetry.goodput``
        summarizes)."""
        total = self.now() if total_wall_s is None else float(total_wall_s)
        attributed = sum(self.seconds.values())
        other = max(0.0, total - attributed)
        overrun = max(0.0, attributed - total)
        seconds = {b: round(self.seconds.get(b, 0.0), 6) for b in BUCKETS}
        seconds["other"] = round(seconds.get("other", 0.0) + other, 6)
        chip_seconds = {
            b: round(self.chip_seconds.get(b, 0.0), 6) for b in BUCKETS
        }
        chip_seconds["other"] = round(
            chip_seconds.get("other", 0.0) + other * self.chips, 6
        )
        total_chip = total * self.chips
        return {
            "record": "goodput",
            "scope": scope,
            "lineage_id": self.lineage_id,
            "attempt": self.attempt,
            "attempts": self.attempt,
            "chips": self.chips,
            "total_wall_s": round(total, 6),
            "total_chip_s": round(total_chip, 6),
            "seconds": seconds,
            "chip_seconds": chip_seconds,
            "fraction_useful": round(
                chip_seconds["useful_step"] / total_chip, 6
            ) if total_chip > 0 else None,
            "steps": dict(self.step_counts),
            "replayed_steps_count": self.step_counts.get(
                "replayed_steps", 0
            ),
            "sum_check": sum_check(seconds, total),
            **({"overrun_s": round(overrun, 6)} if overrun else {}),
            "time_origin_unix_s": self.t0_unix,
            "windows": self._coalesced_windows(),
            **(
                {"windows_truncated": self.windows_truncated}
                if self.windows_truncated else {}
            ),
            **extra,
        }


def sum_check(seconds: dict, total_wall_s: float,
              tolerance: float = SUM_TOLERANCE) -> dict:
    """The pinned decomposition contract: every bucket (incl. the
    ``other`` residual) sums to the total wall within ``tolerance``.
    Because ``other`` absorbs any shortfall, the only way to fail is
    OVER-attribution — a double-billed window."""
    s = sum(float(v or 0.0) for v in seconds.values())
    total = float(total_wall_s)
    dev = abs(s - total)
    return {
        "attributed_s": round(s, 6),
        "total_wall_s": round(total, 6),
        "tolerance": tolerance,
        "ok": dev <= tolerance * max(total, 1e-9),
    }


# ----------------------------------------------------------- lineage merge


def failed_attempt_facts(flight_doc: dict,
                         durable_step: int | None) -> dict:
    """Price a dead attempt from its flight dump: the resumable step
    walls at-or-below the durable checkpoint are vouched-for useful
    work; the walls past it are the lost tail the resume re-pays.
    The retry parent calls this BEFORE the next attempt's dump
    replaces the file."""
    useful = lost = 0.0
    n_useful = n_lost = 0
    for r in (flight_doc or {}).get("records", []):
        if r.get("kind") != "step" or not r.get("resumable"):
            continue
        w = r.get("wall_s")
        step = r.get("step")
        if not isinstance(w, (int, float)) or not isinstance(step, int):
            continue
        if durable_step is not None and step <= durable_step:
            useful += float(w)
            n_useful += 1
        else:
            lost += float(w)
            n_lost += 1
    return {
        "useful_wall_s": round(useful, 6),
        "lost_wall_s": round(lost, 6),
        "useful_steps": n_useful,
        "lost_steps": n_lost,
        **(
            {"durable_step": durable_step}
            if durable_step is not None else {}
        ),
    }


def merge_lineage(final: dict | None, failures: list[dict],
                  *, lineage_id: str | None = None) -> dict | None:
    """Fold every attempt of a lineage onto one wall-clock axis.

    ``final`` is the surviving attempt's goodput doc (its own
    decomposition); each entry of ``failures`` is a retry JSONL record,
    extended by the parent with a ``goodput`` sub-cell
    (:func:`failed_attempt_facts`) plus ``wall_s`` / ``backoff_s``.
    A failed attempt's durable-step walls count ``useful_step``; its
    lost tail and the backoff bill ``recovery`` (work the resume
    re-pays + dead waiting); its unattributed setup is ``other``.
    Returns None when there is nothing to merge (no final doc and no
    failures)."""
    failures = [f for f in (failures or []) if isinstance(f, dict)]
    if final is None and not failures:
        return None
    chips = int((final or {}).get("chips") or 1)
    seconds = {b: 0.0 for b in BUCKETS}
    windows: list[dict] = []
    attempts_detail: list[dict] = []
    cursor = 0.0  # lineage-axis seconds consumed by prior attempts
    for f in failures:
        wall = float(f.get("wall_s") or 0.0)
        backoff = float(f.get("backoff_s") or 0.0)
        gp = f.get("goodput") if isinstance(f.get("goodput"), dict) else {}
        useful = min(float(gp.get("useful_wall_s") or 0.0), wall)
        lost = min(float(gp.get("lost_wall_s") or 0.0), wall - useful)
        setup = max(0.0, wall - useful - lost)
        seconds["useful_step"] += useful
        seconds["recovery"] += lost + backoff
        seconds["other"] += setup
        # coarse windows for the trace: the dead attempt's span on the
        # lineage axis — setup, then the vouched-for useful run, then
        # the lost tail + backoff as one recovery window
        t = cursor
        if setup:
            windows.append({"bucket": "other", "t0_s": round(t, 6),
                            "t1_s": round(t + setup, 6),
                            "attempt": f.get("attempt")})
            t += setup
        if useful:
            windows.append({"bucket": "useful_step", "t0_s": round(t, 6),
                            "t1_s": round(t + useful, 6),
                            "attempt": f.get("attempt")})
            t += useful
        if lost + backoff:
            windows.append({"bucket": "recovery", "t0_s": round(t, 6),
                            "t1_s": round(t + lost + backoff, 6),
                            "attempt": f.get("attempt"),
                            "reason": f.get("reason")})
        attempts_detail.append({
            "attempt": f.get("attempt"),
            "outcome": "failed",
            "reason": f.get("reason"),
            "wall_s": round(wall, 6),
            "backoff_s": round(backoff, 6),
            **gp,
        })
        cursor += wall + backoff
    total = cursor
    if final is not None:
        for b in BUCKETS:
            seconds[b] += float((final.get("seconds") or {}).get(b) or 0.0)
        for w in final.get("windows") or []:
            windows.append({
                **w,
                "t0_s": round(w["t0_s"] + cursor, 6),
                "t1_s": round(w["t1_s"] + cursor, 6),
            })
        total = cursor + float(final.get("total_wall_s") or 0.0)
        attempts_detail.append({
            "attempt": final.get("attempt"),
            "outcome": "succeeded",
            "wall_s": final.get("total_wall_s"),
            "fraction_useful": final.get("fraction_useful"),
        })
    seconds = {b: round(seconds[b], 6) for b in BUCKETS}
    total_chip = total * chips
    chip_seconds = {b: round(seconds[b] * chips, 6) for b in BUCKETS}
    lineage_unix0 = None
    if final is not None and final.get("time_origin_unix_s") is not None:
        lineage_unix0 = final["time_origin_unix_s"] - cursor
    return {
        "record": "goodput",
        "scope": "train_lineage",
        # identity (strategy/mesh) rides through from the surviving
        # attempt so the parent can key the lineage's ledger row
        **{
            k: final[k] for k in ("strategy", "mesh")
            if final is not None and final.get(k) is not None
        },
        "lineage_id": lineage_id or (final or {}).get("lineage_id"),
        "attempts": len(failures) + (1 if final is not None else 0),
        "chips": chips,
        "total_wall_s": round(total, 6),
        "total_chip_s": round(total_chip, 6),
        "seconds": seconds,
        "chip_seconds": chip_seconds,
        "fraction_useful": round(
            chip_seconds["useful_step"] / total_chip, 6
        ) if total_chip > 0 else None,
        "replayed_steps_count": (final or {}).get(
            "replayed_steps_count", 0
        ),
        "sum_check": sum_check(seconds, total),
        **(
            {"time_origin_unix_s": lineage_unix0}
            if lineage_unix0 is not None else {}
        ),
        "attempts_detail": attempts_detail,
        "windows": windows,
    }


# --------------------------------------------------------- serving goodput


def serve_goodput_cell(
    done,
    *,
    clock: str,
    wall_s: float | None,
    n_chips: int = 1,
    offered: int = 0,
    rejected: int = 0,
    completed: int = 0,
    dropped: int = 0,
    drain_demand: int = 0,
    slo: dict | None = None,
) -> dict:
    """SLO-denominated serving goodput over COMPLETED requests.

    ``done`` is the engine's completed :class:`~ddl25spring_tpu.serve.
    engine.Request` list (or dicts with the same fields): TTFT =
    ``first_token_t - arrival_t`` and per-token latency =
    ``(done_t - first_token_t) / (tokens - 1)`` are judged on the
    ENGINE clock ``clock`` ("virtual" on deterministic arms — exactly
    where wall is noise-bound, so attainment is reproducible on any
    host).  Goodput tokens/sec/chip counts the SLO-compliant completed
    tokens only; availability charges every request the engine turned
    away or failed to finish: rejects at the door, accepted-then-
    dropped, and the drain-window demand (handoff re-submissions —
    served capacity the reshape consumed twice)."""
    slo = dict(slo or serve_slo())
    ttft_max = float(slo["ttft_ms"]) / 1e3
    tok_max = float(slo["tok_ms"]) / 1e3

    def _get(r, name):
        return r.get(name) if isinstance(r, dict) else getattr(r, name, None)

    evaluated = compliant = 0
    compliant_tokens = completed_tokens = 0
    ttft_misses = tok_misses = 0
    for r in done or []:
        arr, ftk = _get(r, "arrival_t"), _get(r, "first_token_t")
        dne = _get(r, "done_t")
        toks = _get(r, "tokens")
        n_tok = len(toks) if toks is not None else 0
        if arr is None or ftk is None or dne is None or not n_tok:
            continue
        evaluated += 1
        completed_tokens += n_tok
        ttft = ftk - arr
        tok_lat = (dne - ftk) / max(1, n_tok - 1)
        ttft_ok = ttft <= ttft_max
        tok_ok = tok_lat <= tok_max
        ttft_misses += 0 if ttft_ok else 1
        tok_misses += 0 if tok_ok else 1
        if ttft_ok and tok_ok:
            compliant += 1
            compliant_tokens += n_tok
    offered = max(int(offered), 0)
    unavailable = int(rejected) + int(dropped) + int(drain_demand)
    return {
        "slo": {**slo, "clock": clock},
        "requests_evaluated": evaluated,
        "slo_compliant": compliant,
        "slo_attainment": (
            round(compliant / evaluated, 6) if evaluated else None
        ),
        "ttft_misses": ttft_misses,
        "tok_latency_misses": tok_misses,
        "completed_tokens": completed_tokens,
        "slo_compliant_tokens": compliant_tokens,
        "goodput_tokens_per_sec_per_chip": (
            round(compliant_tokens / wall_s / max(1, n_chips), 3)
            if wall_s else None
        ),
        "offered": offered,
        "rejected": int(rejected),
        "dropped": int(dropped),
        "drain_demand": int(drain_demand),
        "completed": int(completed),
        "availability": (
            round(max(0.0, 1.0 - unavailable / offered), 6)
            if offered else None
        ),
    }


# ---------------------------------------------------------------- artifacts


def write_run_goodput(doc: dict, run_dir: str) -> str:
    """Atomic ``goodput.json`` in the run dir (temp + rename, the
    repo's dump idiom).  The retry parent REWRITES it with the merged
    lineage view after the surviving child wrote its attempt view."""
    os.makedirs(run_dir, exist_ok=True)
    path = os.path.join(run_dir, GOODPUT_BASENAME)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, default=str, allow_nan=False)
    os.replace(tmp, path)
    return path


def read_run_goodput(run_dir: str) -> dict | None:
    """``goodput.json`` from a run dir, or None when the run never
    wrote one (obs off / pre-PR-20 artifacts)."""
    path = os.path.join(run_dir, GOODPUT_BASENAME)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def goodput_cell(doc: dict | None) -> dict:
    """The ``telemetry.goodput`` cell: the decomposition summary
    without the window list (BENCH lines stay one readable JSON
    line)."""
    if not isinstance(doc, dict):
        return {"enabled": False}
    return {
        k: doc.get(k)
        for k in (
            "scope", "lineage_id", "attempt", "attempts", "chips",
            "total_wall_s", "seconds", "fraction_useful",
            "replayed_steps_count", "sum_check", "slo_attainment",
            "availability", "goodput_tokens_per_sec_per_chip",
        )
        if doc.get(k) is not None
    } or {"enabled": False}


def ledger_row(
    doc: dict,
    *,
    strategy: str,
    mesh: dict | None,
    host: dict | str | None,
    git_sha: str | None = None,
    extra_key: dict | None = None,
) -> dict:
    """One ``record: "goodput"`` trend row for ``runs/perf_ledger.
    jsonl`` — keyed (strategy, mesh, host, scope) like every other
    ledger kind so ``goodput_report --check`` bands the fraction over
    run history; the lineage id rides as identity, never as part of
    the trend key (every lineage is unique — keying on it would orphan
    every group)."""
    return {
        "record": "goodput",
        "ts": time.time(),
        **({"git_sha": git_sha} if git_sha else {}),
        **({"host": host} if host else {}),
        "key": {
            "strategy": strategy,
            "mesh": dict(mesh or {}),
            "scope": doc.get("scope"),
            **(extra_key or {}),
        },
        "lineage_id": doc.get("lineage_id"),
        "attempts": doc.get("attempts"),
        "chips": doc.get("chips"),
        "total_wall_s": doc.get("total_wall_s"),
        "fraction_useful": doc.get("fraction_useful"),
        "seconds": doc.get("seconds"),
        "replayed_steps_count": doc.get("replayed_steps_count"),
        "sum_check": doc.get("sum_check"),
        **(
            {
                "slo_attainment": doc.get("slo_attainment"),
                "availability": doc.get("availability"),
                "goodput_tokens_per_sec_per_chip": doc.get(
                    "goodput_tokens_per_sec_per_chip"
                ),
            }
            if doc.get("scope") == "serve" else {}
        ),
    }


__all__ = [
    "BUCKETS",
    "ENV_ATTEMPT",
    "ENV_LINEAGE",
    "ENV_SLO_TOK_MS",
    "ENV_SLO_TTFT_MS",
    "GOODPUT_BASENAME",
    "GoodputMeter",
    "MAX_WINDOWS",
    "SUM_TOLERANCE",
    "failed_attempt_facts",
    "goodput_cell",
    "ledger_row",
    "lineage_from_env",
    "merge_lineage",
    "mint_lineage_id",
    "read_run_goodput",
    "serve_goodput_cell",
    "serve_slo",
    "sum_check",
    "write_run_goodput",
]
