"""graft-trace: the unified run timeline (PR 16).

One run, one ordered event log.  Before this module the run's story was
scattered across six uncorrelated artifacts (``flight.json``,
``metrics.jsonl``, ``serve.json``, ``trace.json``, ``chaos_fired.jsonl``,
``perf_ledger.jsonl``); :class:`Timeline` gives every subsystem a single
typed, append-only stream to emit into, so "why was THIS request slow?"
has an answer instead of a p95.

Design (deliberately the flight recorder's, one layer up):

- **Typed.**  Every event kind is declared in :data:`EVENT_KINDS` with
  its required payload fields; :meth:`Timeline.emit` refuses unknown
  kinds and missing fields loudly.  The schema is the contract
  ROADMAP-5's FL/RL workloads emit into for free — adding a kind is one
  table row, not a new file format.
- **Append-only JSONL + ring.**  When :meth:`Timeline.configure` names a
  run dir, events stream to ``timeline.jsonl`` (one strict-JSON object
  per line, flushed per write, NaN refused — the
  :func:`~ddl25spring_tpu.obs.logger.read_jsonl` idiom).  A bounded ring
  (:data:`DEFAULT_CAPACITY`) always holds the tail regardless, so
  in-process consumers (reports, tests) never touch the filesystem.
- **Crash-flushed through the flight shutdown chain.**  ``configure``
  registers :meth:`Timeline.flush` via
  :meth:`~ddl25spring_tpu.obs.recorder.FlightRecorder.register_shutdown`
  — bounded and idempotent per that contract — so an excepthook /
  SIGTERM / atexit dump carries the timeline's last buffered lines too.
- **Two clocks.**  Every event stamps ``t_wall_s`` (host wall, this
  timeline's perf-counter origin; ``time_origin_unix_s`` in the header
  anchors it to unix time for cross-artifact merging).  Serve events add
  ``vt_s`` — the engine clock, *virtual* on deterministic A/B arms — so
  replayed runs stay comparable event-for-event while wall time records
  what the host actually paid.
- **Gated like everything in obs.**  :meth:`emit` is a no-op unless
  :func:`ddl25spring_tpu.obs.state.enabled`; emission is host-side only
  and never consumes RNG or advances an engine clock, so ``DDL25_OBS=0``
  leaves compiled HLO byte-identical and serve token streams bitwise
  unchanged (pinned in ``tests/test_timeline.py``).

Subsystems that already narrate into the flight ring (chaos fires,
reshapes, autosave save/skip/restore, watchdog stalls, sentinel
violations) are mirrored into the timeline through a
:meth:`~ddl25spring_tpu.obs.recorder.FlightRecorder.add_tap` hook —
one wiring point instead of six edited call sites.  The serve engine and
driver emit their richer request-lifecycle events directly.

``tools/trace_export.py`` merges this log with the ``obs/spans.py`` host
spans and the flight ring into one multi-track Perfetto/Chrome trace.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable

from ddl25spring_tpu.analysis.host_sanitizer import wrap_lock
from ddl25spring_tpu.obs import state
from ddl25spring_tpu.obs.recorder import _json_safe, flight

TIMELINE_BASENAME = "timeline.jsonl"
DEFAULT_CAPACITY = 4096

# ------------------------------------------------------------------ schema
#
# kind -> required payload fields.  Optional fields ride along freely
# (every event also carries record/seq/kind/t_wall_s, plus vt_s /
# engine / replica when the emitter supplies them); *required* fields
# are the contract reports and the trace exporter key on.
EVENT_KINDS: dict[str, tuple[str, ...]] = {
    # -- serve request lifecycle (serve/engine.py, serve/driver.py) --
    "serve_submit": ("rid", "prompt_len", "max_new"),
    "serve_reject": ("rid", "reason"),
    "serve_admit": ("rid", "slot"),
    "serve_prefill": ("rid", "slot", "start", "prefix_hit_tokens"),
    "serve_first_token": ("rid", "ttft_s"),
    "serve_spec_round": ("rid", "round", "accepted", "rejected"),
    "serve_done": ("rid", "tokens"),
    "serve_drain": ("requeued",),
    "serve_drain_handoff": ("rid", "from_replica"),
    # -- reshape windows (serve/driver.elastic_serve_run) --
    "reshape_end": ("reason", "t", "t_end"),
    # -- graft-mem resource samples (obs/memscope.MemScope.sample):
    # live_bytes required; rss_bytes / pool_used / queue_depth /
    # tokens_per_s ride along and become Perfetto counter tracks in
    # tools/trace_export.py --
    "mem_sample": ("live_bytes",),
    # -- mirrored off the flight ring (FlightRecorder tap) --
    "mem": (),  # graft-mem growth-detector violations
    "chaos": (),
    "reshape": (),
    "save": (),
    "save_skipped": (),
    "restore": (),
    "stall": (),
    "violation": (),
}

#: flight-ring kinds the tap mirrors into the timeline.  Serve flight
#: kinds (``serve_prefill``/``serve_tick``/``serve_spec``) are NOT
#: mirrored — the engine emits richer per-request events directly.
MIRRORED_FLIGHT_KINDS = frozenset(
    k for k, req in EVENT_KINDS.items() if not req
)


class Timeline:
    """Run-scoped structured event log: bounded ring + optional
    append-only JSONL stream, crash-flushed through the flight
    recorder's shutdown chain.  Thread-safe; a module singleton
    (:data:`timeline`) serves the whole process, like ``flight``."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = wrap_lock("timeline._lock", threading.RLock())
        self._ring: deque = deque(maxlen=capacity)
        self._counts: dict[str, int] = {}
        self._seq = 0
        self._t0 = time.perf_counter()
        self._t0_unix = time.time()
        self._stream = None
        self._hooked = False
        self.path: str | None = None

    # ------------------------------------------------------- lifecycle

    def configure(self, run_dir: str | None = None,
                  capacity: int | None = None,
                  meta: dict | None = None) -> None:
        """(Re)target the timeline at a run directory.  Opens a fresh
        ``timeline.jsonl`` (header line first), resets seq/ring/clock
        origin — one configure == one run — and registers the crash
        flush with the flight shutdown chain.  ``run_dir=None`` closes
        the stream (events still ring in memory).  ``meta`` merges
        extra identity fields into the header line (bench stamps the
        retry ``lineage_id`` / ``attempt`` here so every attempt's
        timeline names the lineage it belongs to); reserved header
        keys win over collisions."""
        with self._lock:
            self.close()
            if capacity is not None:
                self._ring = deque(self._ring, maxlen=int(capacity))
            self._ring.clear()
            self._counts = {}
            self._seq = 0
            self._t0 = time.perf_counter()
            self._t0_unix = time.time()
            if run_dir is None:
                return
            os.makedirs(run_dir, exist_ok=True)
            self.path = os.path.join(run_dir, TIMELINE_BASENAME)
            self._stream = open(self.path, "w")
            header = {
                **(meta or {}),
                "record": "timeline_header",
                "time_origin_unix_s": self._t0_unix,
                "capacity": self._ring.maxlen,
                "pid": os.getpid(),
            }
            self._stream.write(
                json.dumps(_json_safe(header), allow_nan=False) + "\n"
            )
            self._stream.flush()
            if not self._hooked:
                flight.register_shutdown(self.flush, "timeline")
                self._hooked = True

    def flush(self) -> None:
        """Flush the JSONL stream (bounded + idempotent: safe on the
        flight shutdown chain, safe to call twice, safe when closed)."""
        with self._lock:
            s = self._stream
            if s is not None and not s.closed:
                s.flush()
                try:
                    os.fsync(s.fileno())
                except OSError:  # pragma: no cover - exotic filesystems
                    pass

    def close(self) -> None:
        """Flush and close the stream; the ring stays readable."""
        with self._lock:
            if self._stream is not None:
                if not self._stream.closed:
                    self._stream.flush()
                    self._stream.close()
                self._stream = None
            self.path = None

    # --------------------------------------------------------- emission

    def emit(self, kind: str, *, vt: float | None = None,
             engine: str | None = None, replica: int | None = None,
             **fields: Any) -> dict | None:
        """Append one typed event.  No-op (``None``) when obs is
        disabled.  Raises ``ValueError`` on an unknown kind or a missing
        required field — the schema is a contract, not a convention.
        Reserved envelope keys win over payload collisions."""
        if not state.enabled():
            return None
        required = EVENT_KINDS.get(kind)
        if required is None:
            raise ValueError(
                f"unknown timeline event kind {kind!r} — declare it in "
                f"obs.timeline.EVENT_KINDS"
            )
        missing = [f for f in required if f not in fields]
        if missing:
            raise ValueError(
                f"timeline event {kind!r} missing required field(s) "
                f"{missing}"
            )
        with self._lock:
            rec = {
                **fields,
                "record": "event",
                "seq": self._seq,
                "kind": kind,
                "t_wall_s": round(time.perf_counter() - self._t0, 6),
            }
            if vt is not None:
                rec["vt_s"] = round(float(vt), 6)
            if engine is not None:
                rec["engine"] = engine
            if replica is not None:
                rec["replica"] = int(replica)
            self._seq += 1
            self._counts[kind] = self._counts.get(kind, 0) + 1
            self._ring.append(rec)
            if self._stream is not None and not self._stream.closed:
                self._stream.write(
                    json.dumps(_json_safe(rec), allow_nan=False) + "\n"
                )
                self._stream.flush()
            return rec

    # ------------------------------------------------------ inspection

    def counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def events(self, kind: str | None = None) -> list[dict]:
        """The ring's current contents (oldest first), optionally
        filtered by kind."""
        with self._lock:
            evs = list(self._ring)
        if kind is not None:
            evs = [e for e in evs if e.get("kind") == kind]
        return evs

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "record": "timeline",
                "emitted": self._seq,
                "counts": dict(self._counts),
                "time_origin_unix_s": self._t0_unix,
                "path": self.path,
            }


#: process-wide singleton, mirroring ``obs.recorder.flight``.
timeline = Timeline()


def _flight_tap(rec: dict) -> None:
    """Mirror narrating flight kinds into the timeline (installed on the
    module-singleton ``flight`` at import).  Envelope keys from the
    flight record (seq / t_s) are renamed so the timeline's own
    envelope wins."""
    if rec.get("kind") not in MIRRORED_FLIGHT_KINDS:
        return
    payload = {
        ("flight_" + k if k in ("seq", "t_s", "kind", "record") else k): v
        for k, v in rec.items()
        if k != "kind"
    }
    timeline.emit(rec["kind"], **payload)


flight.add_tap(_flight_tap)


# ------------------------------------------------------------------ readers


def read_timeline(run_dir: str) -> tuple[dict, list[dict]]:
    """Load ``timeline.jsonl`` from a run dir: ``(header, events)``.
    Strict JSON (NaN/Infinity refused, matching the writer); raises
    ``FileNotFoundError`` when the run never configured a timeline."""
    path = os.path.join(run_dir, TIMELINE_BASENAME)
    header: dict = {}
    events: list[dict] = []

    def _reject(_):
        raise ValueError("non-finite constant in timeline.jsonl")

    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line, parse_constant=_reject)
            if rec.get("record") == "timeline_header":
                header = rec
            else:
                events.append(rec)
    return header, events


__all__ = [
    "EVENT_KINDS",
    "MIRRORED_FLIGHT_KINDS",
    "TIMELINE_BASENAME",
    "Timeline",
    "read_timeline",
    "timeline",
]
