"""Crash-surviving flight recorder: the last N step records, dumpable.

BENCH rounds r01–r05 all died with ``accelerator unreachable: device
init timed out`` and left **zero post-mortem state** — the JSON error
line was the entire forensic record.  The flight recorder closes that
gap: a thread-safe ring buffer of the last ``N`` step records (step
index, sentinel values, wall/dispatch timings, strategy, plus whatever
run metadata — mesh, layout, RNG seed — the driver annotates), persisted
as a structured ``flight.json`` on

- **unhandled exception** (a chained ``sys.excepthook``),
- **SIGTERM** (the scheduler-kill path; the previous handler is chained),
- **interpreter exit** (``atexit``, skipped when a dump already covers
  the latest records),
- **explicit calls** — the sentinel ``halt`` policy and the stall
  watchdog both dump through here,

so a dead run is diagnosable from artifacts alone.  Recording is pure
host-side bookkeeping (a deque append under a lock) — nothing here ever
touches a traced program, so it is always on wherever a driver calls
it; the handlers install only on request (:meth:`FlightRecorder.
install`), never at import.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import math
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Any

from ddl25spring_tpu.analysis.host_sanitizer import wrap_lock
from ddl25spring_tpu.utils.config import env_float

DEFAULT_CAPACITY = 256
FLIGHT_BASENAME = "flight.json"
_UNSET = object()  # configure() sentinel: "leave as is" vs "clear"


def default_flight_dir() -> str:
    """Where dumps land when no run dir was configured: the
    ``DDL25_FLIGHT_DIR`` env (through the sanctioned boundary's module —
    a plain read here since this is host-only code) or ``runs/flight``."""
    return os.environ.get("DDL25_FLIGHT_DIR") or os.path.join(
        "runs", "flight"
    )


def _json_safe(v: Any):
    """NaN/Inf are exactly what flight records carry on the day they
    matter — encode them as strings so the dump stays strict JSON.
    Foreign scalar types (numpy float32 losses, jax ints in annotate())
    coerce through ``float``/``str``: a crash dump must never fail on
    the shape of what it is recording."""
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, float):
        return v if math.isfinite(v) else repr(v)  # 'nan', 'inf', '-inf'
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    try:  # numpy/jax scalars and anything float-like
        return _json_safe(float(v))
    except (TypeError, ValueError):
        return str(v)


class FlightRecorder:
    """Thread-safe ring buffer of run-health records + dump machinery."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        # REENTRANT on purpose: the SIGTERM handler runs on the main
        # thread and walks the shutdown hooks + dump() — both of which
        # take this lock — and the signal can land while that same
        # thread is inside record()'s critical section (it runs every
        # step).  A plain Lock would self-deadlock the preemption path;
        # reentrancy at worst lets the handler observe a half-applied
        # record update (an off-by-one "recorded" count in the dump),
        # which a dying process tolerates.  DDL25_SANITIZE=1 wraps it
        # in the graft-race order-recording proxy (a no-op pass-through
        # otherwise).
        self._lock = wrap_lock("flight._lock", threading.RLock())
        self._records: deque[dict] = deque(maxlen=capacity)
        self._meta: dict[str, Any] = {}
        self._seq = 0
        # cumulative per-kind counters, ring-eviction-proof: a violation
        # recorded 1000 steps ago must still fail --check-health even
        # after the ring rolled past it, and the recovery report counts
        # saves/restores the same way
        self._counts: dict[str, int] = {}
        self._last: dict[str, dict] = {}
        # shutdown hooks: callables the crash paths run BEFORE dumping
        # (checkpoint barriers, flushes) so the dump names what they
        # made durable — see register_shutdown
        self._shutdown_hooks: dict[str, Any] = {}
        self._run_dir: str | None = None
        self._t0 = time.perf_counter()
        self._t0_unix = time.time()
        self._last_beat = time.perf_counter()
        # taps: callables invoked with every record (outside the lock,
        # exceptions suppressed) — how obs.timeline mirrors narrating
        # kinds (chaos/reshape/save/stall/violation…) into the unified
        # event log without editing six call sites.  Taps survive
        # reset(): they are wiring, not run state.
        self._taps: list = []
        self._dumped_seq = -1
        self._installed = False
        self._prev_excepthook = None
        self._prev_sigterm = None

    # ---- recording ------------------------------------------------------

    def configure(self, run_dir=_UNSET, capacity: int | None = None) -> None:
        """Set the dump directory and/or ring capacity.  ``run_dir=None``
        CLEARS a previously-set directory (back to the
        :func:`default_flight_dir` fallback) — the distinction from
        "not passed" matters for anything resetting the shared
        recorder, or a stale test/run dir leaks into later dumps."""
        with self._lock:
            if run_dir is not _UNSET:
                self._run_dir = run_dir
            if capacity is not None and capacity != self._records.maxlen:
                self._records = deque(self._records, maxlen=capacity)

    def annotate(self, **meta: Any) -> None:
        """Attach run-level facts (strategy, mesh, layout, RNG seed…)
        that every dump should carry; last write per key wins."""
        with self._lock:
            self._meta.update(meta)

    def record(
        self, kind: str = "step", *, touch: bool = True, **fields: Any
    ) -> dict:
        """Append one record to the ring; returns it (with ``seq`` and
        wall-clock offsets assigned).  Cheap: one locked deque append.
        ``touch=False`` records WITHOUT counting as liveness — the
        stall watchdog uses it so its own stall record doesn't read as
        the progress that would re-arm it mid-stall."""
        now = time.perf_counter()
        with self._lock:
            rec = {
                "seq": self._seq,
                "kind": kind,
                "t_s": round(now - self._t0, 6),
                **fields,
            }
            self._seq += 1
            self._records.append(rec)
            self._counts[kind] = self._counts.get(kind, 0) + 1
            self._last[kind] = rec
            if touch:
                self._last_beat = now
        # taps run OUTSIDE the lock: a tap appends into its own
        # lock-guarded structure (the timeline), and lock nesting across
        # modules is how shutdown-path deadlocks are born.  A tap must
        # never take down the subsystem that is narrating.
        for tap in list(self._taps):
            try:
                tap(rec)
            except Exception:  # noqa: BLE001 - observability stays passive
                pass
        return rec

    def add_tap(self, fn) -> None:
        """Subscribe ``fn(record)`` to every :meth:`record` call.
        Idempotent per callable; taps persist across :meth:`reset`."""
        with self._lock:
            if fn not in self._taps:
                self._taps.append(fn)

    def remove_tap(self, fn) -> None:
        with self._lock:
            if fn in self._taps:
                self._taps.remove(fn)

    def beat(self) -> None:
        """Liveness tick without a record — the watchdog's heartbeat."""
        with self._lock:
            self._last_beat = time.perf_counter()

    def seconds_since_beat(self) -> float:
        with self._lock:
            return time.perf_counter() - self._last_beat

    def last(self, n: int | None = None) -> list[dict]:
        with self._lock:
            recs = list(self._records)
        return recs if n is None else recs[-n:]

    def counts(self) -> dict[str, int]:
        """Cumulative per-kind record counts (O(kinds), no ring copy) —
        the cheap poll the autosave gate and telemetry cells use."""
        with self._lock:
            return dict(self._counts)

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "meta": dict(self._meta),
                "capacity": self._records.maxlen,
                "recorded": self._seq,
                # anchors record t_s offsets to unix time so
                # tools/trace_export.py can merge the ring with the
                # span recorder and the timeline on one axis
                "time_origin_unix_s": self._t0_unix,
                "violations": self._counts.get("violation", 0),
                "stalls": self._counts.get("stall", 0),
                "counts": dict(self._counts),
                **{
                    f"last_{k}": dict(r) for k, r in self._last.items()
                },
                "records": [dict(r) for r in self._records],
            }

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._meta.clear()
            self._seq = 0
            self._counts = {}
            self._last.clear()
            self._shutdown_hooks.clear()
            self._dumped_seq = -1
            self._t0 = time.perf_counter()
            self._t0_unix = time.time()
            self._last_beat = time.perf_counter()

    # ---- dumping --------------------------------------------------------

    def dump(
        self,
        path: str | None = None,
        reason: str = "manual",
        extra: dict | None = None,
    ) -> str:
        """Write ``flight.json`` (atomically: temp file + rename, so a
        crash mid-dump never leaves a truncated artifact where a good
        one could have been) and return its path."""
        if path is None:
            d = self._run_dir or default_flight_dir()
            path = os.path.join(d, FLIGHT_BASENAME)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        doc = self.snapshot()
        doc["record"] = "flight"
        doc["reason"] = reason
        doc["dumped_at_unix"] = time.time()
        # violations/stalls ride the CUMULATIVE counters (snapshot), not
        # a recount of the bounded ring — a violation recorded hundreds
        # of steps before an end_of_run/atexit dump must still fail the
        # --check-health gate after the ring evicted it, and a later
        # dump must not erase an earlier watchdog fire.  The watchdog's
        # own dump overrides `stall` with its richer point-in-time info
        # (thread stacks) via ``extra``.
        last_stall = doc.pop("last_stall", None)
        if last_stall is not None:
            doc["stall"] = {
                k: v for k, v in last_stall.items()
                if k not in ("seq", "kind")
            }
        # graft-mem (PR 17): every dump carries the live-array picture
        # (count, total bytes, top-10 largest with shape/dtype/sharding)
        # + host RSS, so an OOM-shaped death is diagnosable from
        # flight.json alone.  Suppressed wholesale: a crash dump must
        # succeed even with jax half-torn-down.
        with contextlib.suppress(Exception):
            from ddl25spring_tpu.obs import memscope

            doc["live_arrays"] = memscope.live_array_summary(top=10)
            rss = memscope.host_rss_bytes()
            if rss is not None:
                doc["host_rss_bytes"] = rss
        if extra:
            doc.update(extra)
        # pid AND thread id: the watchdog's monitor thread and the main
        # thread's excepthook/halt can dump concurrently — two writers
        # sharing one temp name would interleave and break atomicity
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(_json_safe(doc), f, indent=1, allow_nan=False)
        os.replace(tmp, path)
        with self._lock:
            # mark only the SNAPSHOTTED records as dumped: a record
            # appended on another thread mid-write is not in this
            # artifact, and the atexit pending-check must still see it
            self._dumped_seq = max(self._dumped_seq, doc["recorded"])
        return path

    # ---- shutdown hooks -------------------------------------------------

    def register_shutdown(self, fn, name: str | None = None) -> str:
        """Chain ``fn`` into every crash path this recorder owns —
        excepthook, SIGTERM, atexit — running BEFORE the flight dump so
        the dump records what the hook made durable.  The canonical
        client is :meth:`ft.autosave.AutoSaver.close`: a SIGTERM'd run
        barriers its in-flight checkpoint instead of truncating it.

        Hooks must bound their own runtime (a wedged hook on the
        SIGTERM path would out-wait the scheduler's kill grace — the
        autosave barrier takes a timeout for exactly this reason) and
        be idempotent (the atexit pass runs them again after a SIGTERM
        that chose not to exit).  Returns the registration name for
        :meth:`unregister_shutdown`."""
        name = name or f"hook-{id(fn):x}"
        with self._lock:
            self._shutdown_hooks[name] = fn
        return name

    def unregister_shutdown(self, name: str) -> None:
        with self._lock:
            self._shutdown_hooks.pop(name, None)

    def _run_shutdown_hooks(self, reason: str) -> None:
        del reason  # all paths run all hooks; the arg documents call sites
        with self._lock:
            hooks = list(self._shutdown_hooks.values())
        for fn in hooks:
            # a failing hook must cost neither the dump nor its peers
            with contextlib.suppress(Exception):
                fn()

    # ---- crash handlers -------------------------------------------------

    def install(self, run_dir: str | None = None) -> None:
        """Arm the crash paths: excepthook + SIGTERM + atexit, each
        chaining to whatever was installed before.  Idempotent."""
        if run_dir is not None:
            self.configure(run_dir=run_dir)
        if self._installed:
            return
        self._installed = True

        self._prev_excepthook = sys.excepthook

        def _hook(exc_type, exc, tb):
            # whatever the hooks or dump() hit, the original exception
            # must still reach the user
            self._run_shutdown_hooks("unhandled_exception")
            with contextlib.suppress(Exception):
                self.dump(
                    reason="unhandled_exception",
                    extra={"exception": f"{exc_type.__name__}: {exc}"},
                )
            (self._prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)

        sys.excepthook = _hook

        try:
            prev = signal.getsignal(signal.SIGTERM)

            def _on_term(signum, frame):
                # barrier checkpoints FIRST (each hook bounds itself),
                # so the dump below names the truly durable step; a
                # failed dump must not break signal handling
                self._run_shutdown_hooks("sigterm")
                with contextlib.suppress(Exception):
                    self.dump(reason="sigterm")
                if prev is signal.SIG_IGN:
                    return  # the process chose to ignore TERM: dump only
                if callable(prev):
                    prev(signum, frame)
                else:
                    # exit NOW with the conventional 128+SIGTERM status
                    # (re-delivering through the default handler would
                    # require surviving another interpreter round-trip,
                    # and a dying process owes the world nothing more
                    # than its flight dump).  Caveat shared by any
                    # Python-level handler: a main thread wedged in
                    # native code that holds the GIL never runs this —
                    # the stall watchdog and the driver's hard kill
                    # cover that mode.
                    sys.stderr.flush()
                    os._exit(128 + signum)

            signal.signal(signal.SIGTERM, _on_term)
            self._prev_sigterm = prev
        except (ValueError, OSError):
            # not the main thread (or an exotic platform): the excepthook
            # and atexit paths still cover crashes
            self._prev_sigterm = None

        atexit.register(self._atexit_dump)

    def _atexit_dump(self) -> None:
        # hooks run UNCONDITIONALLY (they are idempotent by contract):
        # an exiting run whose records were already dumped still owes
        # its checkpoint barrier
        self._run_shutdown_hooks("atexit")
        with self._lock:
            pending = self._seq > self._dumped_seq and self._seq > 0
        if pending:
            with contextlib.suppress(Exception):  # exit must stay clean
                self.dump(reason="atexit")

    def uninstall(self) -> None:
        """Disarm the handlers (test harness); atexit's entry becomes a
        no-op via the dumped-seq check rather than unregistration."""
        if not self._installed:
            return
        self._installed = False
        sys.excepthook = self._prev_excepthook or sys.__excepthook__
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except (ValueError, OSError):
                pass
        with contextlib.suppress(Exception):  # best-effort disarm
            atexit.unregister(self._atexit_dump)
        with self._lock:
            self._dumped_seq = self._seq


flight = FlightRecorder()


def watchdog_deadline_default() -> float:
    """The stall watchdog's default deadline (seconds):
    ``DDL25_WATCHDOG_S`` or 900 s — long enough for a cold compile, far
    shorter than a wedged tunnel's forever."""
    return env_float("DDL25_WATCHDOG_S", 900.0)
