"""Host-side span tracer emitting Chrome-trace / Perfetto-loadable JSON.

Why host-side: device-side ``jax.profiler`` capture hangs indefinitely on
tunneled TPU transports (``utils/tracing.py:30-34``, RESULTS §6a), so the
always-available fallback is nested wall-clock spans recorded on the host
and written in the Chrome Trace Event format — loadable in
``chrome://tracing`` / https://ui.perfetto.dev without any XLA profiler
involvement.  Each span *also* enters a ``jax.profiler.TraceAnnotation``,
so on images where the real profiler works the same spans appear inside
the device trace for free (``annotate()``-compatible by construction).

Format: the JSON Object Format — ``{"traceEvents": [...], ...}`` — with
``"X"`` (complete) duration events carrying ``name``/``cat``/``ph``/
``ts``/``dur``/``pid``/``tid``/``args`` and ``"M"`` metadata events naming
the process/threads.  Timestamps are microseconds on a per-recorder
``perf_counter`` origin; the wall-clock anchor rides in ``otherData``.

Thread-safe: spans may open/close concurrently from loader worker threads
and the main loop; event appends are lock-protected and nesting is
per-thread (Chrome's stack-building uses ``tid``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager, nullcontext
from typing import Any, Iterator

from ddl25spring_tpu.obs import state


def _annotation(name: str):
    """``jax.profiler.TraceAnnotation`` when jax is importable (it always
    is in this package, but spans must not *require* a working backend)."""
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - profiler API missing/broken
        return nullcontext()


class SpanRecorder:
    """Collects nested host spans; serializes as Chrome trace JSON."""

    def __init__(self, process_name: str = "ddl25spring_tpu"):
        self._lock = threading.Lock()
        self._events: list[dict[str, Any]] = []
        self._t0 = time.perf_counter()
        self._t0_unix = time.time()
        self.process_name = process_name
        self._named_tids: set[int] = set()
        self._emit_meta(
            {
                "name": "process_name",
                "ph": "M",
                "pid": os.getpid(),
                "tid": 0,
                "args": {"name": process_name},
            }
        )

    def _emit_meta(self, ev: dict[str, Any]) -> None:
        with self._lock:
            self._events.append(ev)

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _ensure_thread_named(self, tid: int) -> None:
        if tid in self._named_tids:
            return
        self._named_tids.add(tid)
        self._events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": os.getpid(),
                "tid": tid,
                "args": {"name": threading.current_thread().name},
            }
        )

    @contextmanager
    def span(self, name: str, cat: str = "host", **args: Any) -> Iterator[None]:
        """Record the block as one complete ("X") event; also annotate the
        real profiler timeline when one is active."""
        tid = threading.get_ident()
        ts = self._now_us()
        with _annotation(name):
            try:
                yield
            finally:
                dur = self._now_us() - ts
                with self._lock:
                    self._ensure_thread_named(tid)
                    self._events.append(
                        {
                            "name": name,
                            "cat": cat,
                            "ph": "X",
                            "ts": ts,
                            "dur": dur,
                            "pid": os.getpid(),
                            "tid": tid,
                            **({"args": args} if args else {}),
                        }
                    )

    def instant(self, name: str, cat: str = "host", **args: Any) -> None:
        """A zero-duration marker ("i" instant event, thread scope)."""
        tid = threading.get_ident()
        with self._lock:
            self._ensure_thread_named(tid)
            self._events.append(
                {
                    "name": name,
                    "cat": cat,
                    "ph": "i",
                    "s": "t",
                    "ts": self._now_us(),
                    "pid": os.getpid(),
                    "tid": tid,
                    **({"args": args} if args else {}),
                }
            )

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def to_chrome_trace(self) -> dict[str, Any]:
        with self._lock:
            events = list(self._events)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "process_name": self.process_name,
                "time_origin_unix_s": self._t0_unix,
            },
        }

    def save(self, path: str) -> str:
        """Write the trace JSON; returns the path (load it in Perfetto)."""
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path


_default = SpanRecorder()


def get_recorder() -> SpanRecorder:
    return _default


def set_recorder(rec: SpanRecorder) -> SpanRecorder:
    """Install a fresh recorder (e.g. one per run dir); returns the old."""
    global _default
    prev, _default = _default, rec
    return prev


def span(name: str, cat: str = "host", **args: Any):
    """Module-level convenience on the default recorder.  A no-op context
    when telemetry is disabled — call sites need no guard."""
    if not state.enabled():
        return nullcontext()
    return _default.span(name, cat=cat, **args)


def instant(name: str, **args: Any) -> None:
    if state.enabled():
        _default.instant(name, **args)
