"""The one global switch for run telemetry.

Everything in :mod:`ddl25spring_tpu.obs` keys off this flag **at trace
time**: when disabled, the instrumentation helpers are Python-level no-ops
that insert nothing into jitted programs, so an instrumented step function
lowers to HLO *identical* to an uninstrumented one (asserted in
``tests/test_obs.py``).  Flipping the flag therefore requires re-tracing
(clear the jit cache or rebuild the step) — the price of true zero cost
when off, which matters more: the bench headline must not carry telemetry
overhead it didn't ask for.

Enable via ``DDL25_OBS=1`` in the environment, :func:`enable`, or the
:func:`scoped` context manager (tests).
"""

from __future__ import annotations

import contextlib
import os

_enabled: bool = os.environ.get("DDL25_OBS", "") not in ("", "0", "false")


def enabled() -> bool:
    """Is telemetry on?  Checked at TRACE time by every obs helper."""
    return _enabled


def enable(on: bool = True) -> None:
    """Turn telemetry on/off globally (affects subsequent traces only)."""
    global _enabled
    _enabled = bool(on)


@contextlib.contextmanager
def scoped(on: bool = True):
    """Temporarily set the telemetry flag (test harness use)."""
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    try:
        yield
    finally:
        _enabled = prev
