"""Structured metrics logger: append-only JSONL with a run-metadata header.

One line per record, flushed as written, so a killed run keeps everything
logged up to the kill — the property the ad-hoc ``print`` lines in
``BENCH_*.json`` provenance never had.  The first line is a ``header``
record carrying the run's identity (mesh shape, layout, git sha, jax
version, device kind); every later line is a ``step`` (or custom) record:

    {"record": "header", "run_id": ..., "mesh": {"data": 2, "stage": 2},
     "layout": "dppp", "git_sha": "...", "jax_version": "...", ...}
    {"record": "step", "step": 0, "wall_s": 0.0312, "samples": 1024,
     "loss": 2.31, ...}

``tools/obs_report.py`` folds a directory of these into a summary table.
"""

from __future__ import annotations

import json
import os
import subprocess
import time
from typing import Any, Iterator


def git_sha(cwd: str | None = None) -> str | None:
    """Best-effort HEAD sha (None outside a repo / without git)."""
    try:
        r = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=cwd or os.path.dirname(os.path.abspath(__file__)),
            capture_output=True,
            text=True,
            timeout=10,
        )
        sha = r.stdout.strip()
        return sha if r.returncode == 0 and sha else None
    except Exception:
        return None


def run_metadata(
    mesh: Any = None, layout: str | None = None, **extra: Any
) -> dict[str, Any]:
    """The header payload: everything needed to interpret the run later.

    ``mesh`` may be a ``jax.sharding.Mesh`` (its ``shape`` mapping is
    recorded) or a plain dict.  ``extra`` lands verbatim (batch size,
    flops_per_step, scan_steps, ...).
    """
    import jax

    shape = None
    if mesh is not None:
        shape = dict(getattr(mesh, "shape", None) or mesh)
    try:
        dev = jax.devices()[0]
        device = {
            "platform": dev.platform,
            "kind": getattr(dev, "device_kind", ""),
            "count": len(jax.devices()),
        }
    except Exception:  # backend init can fail on a dead TPU tunnel
        device = None
    return {
        "record": "header",
        "time_unix_s": time.time(),
        "mesh": shape,
        "layout": layout,
        "git_sha": git_sha(),
        "jax_version": jax.__version__,
        "device": device,
        **extra,
    }


class MetricsLogger:
    """Append-only JSONL writer for one run directory.

    ``MetricsLogger(run_dir, meta=run_metadata(...))`` writes the header
    immediately; ``log(step=..., wall_s=..., ...)`` appends one ``step``
    record per call.  Values that are jax/numpy scalars are coerced to
    Python floats/ints so the lines stay plain JSON.

    Passing ``meta`` marks a FRESH run: any previous ``metrics.jsonl`` in
    the directory is truncated, so re-running into a fixed run dir (e.g.
    ``bench.py --smoke``'s default) never pools two runs' step records
    into one summary.  ``meta=None`` reopens in append mode — the
    crash-resume path, where the earlier records are the point.
    """

    def __init__(
        self,
        run_dir: str,
        meta: dict[str, Any] | None = None,
        filename: str = "metrics.jsonl",
    ):
        self.run_dir = run_dir
        os.makedirs(run_dir, exist_ok=True)
        self.path = os.path.join(run_dir, filename)
        # long-lived handle, closed in close()/__exit__ — not a with-block
        self._f = open(  # noqa: SIM115
            self.path, "w" if meta is not None else "a"
        )
        self._n = 0
        if meta is not None:
            self._write(dict(meta, record=meta.get("record", "header")))

    @staticmethod
    def _coerce(v: Any) -> Any:
        if v is None or isinstance(v, (bool, int, float, str)):
            return v
        if isinstance(v, dict):
            return {k: MetricsLogger._coerce(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [MetricsLogger._coerce(x) for x in v]
        try:  # jax / numpy scalar
            return float(v)
        except Exception:
            return repr(v)

    def _write(self, rec: dict[str, Any]) -> None:
        self._f.write(json.dumps(self._coerce(rec)) + "\n")
        self._f.flush()
        self._n += 1

    def log(self, record: str = "step", **fields: Any) -> None:
        self._write({"record": record, **fields})

    @property
    def lines_written(self) -> int:
        return self._n

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_jsonl(path: str) -> list[dict[str, Any]]:
    """Load every record of a JSONL file (skipping blank lines)."""
    return list(iter_jsonl(path))


def iter_jsonl(path: str) -> Iterator[dict[str, Any]]:
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                yield json.loads(line)
