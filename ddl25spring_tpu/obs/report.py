"""Fold a telemetry run directory into a summary (the analysis half of
``tools/obs_report.py``, importable so ``bench.py`` can embed the same
summary in its JSON line).

A run directory is whatever :class:`~ddl25spring_tpu.obs.logger.
MetricsLogger` + :class:`~ddl25spring_tpu.obs.counters.CounterSet` +
:class:`~ddl25spring_tpu.obs.spans.SpanRecorder` wrote:

    run_dir/metrics.jsonl   header + per-step records   (required)
    run_dir/counters.json   scalar/series/static counters (optional)
    run_dir/trace.json      Chrome-trace host spans       (optional)

The summary derives steps/sec p50/p95 from the per-step ``wall_s``
distribution (p50, not mean — one GC pause must not skew a bench line),
MFU from the header's compiled-FLOPs + chip peak, and the GPipe bubble
fraction from the header's (S, M) with measured tick cadence alongside
when the pipeline counters fired.
"""

from __future__ import annotations

import json
import os
from typing import Any

import numpy as np

from ddl25spring_tpu.obs.counters import gpipe_bubble_fraction
from ddl25spring_tpu.obs.logger import read_jsonl

# the serving artifact a `bench.py --serve` run drops in the obs dir
# (written by ddl25spring_tpu/serve/driver.py, which imports this name
# — the obs layer owns its artifact basenames, like FLIGHT_BASENAME /
# PERF_BASENAME; tools/serve_report.py restates the string to stay
# stdlib-only)
SERVE_BASENAME = "serve.json"


def _pct(xs: list[float], q: float) -> float:
    return float(np.percentile(np.asarray(xs, dtype=np.float64), q))


def _phase_summary(steps: list[dict], header: dict) -> dict[str, Any]:
    # scan-fused dispatches log one record per CALL covering k train steps
    # (wall_s and samples are per-dispatch); normalize everything to
    # per-train-step so fused and unfused phases report the same units
    k = max((int(r.get("fused_steps") or 1) for r in steps), default=1)
    wall = [float(r["wall_s"]) / k for r in steps if r.get("wall_s")]
    out: dict[str, Any] = {"steps": len(steps) * k}
    if k > 1:
        out["fused_steps"] = k
        out["dispatches"] = len(steps)
    if not wall:
        return out
    p50, p95 = _pct(wall, 50), _pct(wall, 95)
    out.update(
        step_s_p50=p50,
        step_s_p95=p95,
        step_s_min=min(wall),
        step_s_mean=sum(wall) / len(wall),
        steps_per_sec_p50=1.0 / p50 if p50 > 0 else None,
        steps_per_sec_p95=1.0 / p95 if p95 > 0 else None,
    )
    samples = [float(r["samples"]) / k for r in steps if r.get("samples")]
    if samples and p50 > 0:
        per_step = samples[0]
        n_chips = int(header.get("n_chips") or 1)
        out["samples_per_sec_p50"] = per_step / p50
        out["samples_per_sec_per_chip_p50"] = per_step / p50 / n_chips
    tokens = [float(r["tokens"]) / k for r in steps if r.get("tokens")]
    if tokens and p50 > 0:
        out["tokens_per_sec_p50"] = tokens[0] / p50
    losses = [float(r["loss"]) for r in steps if r.get("loss") is not None]
    if losses:
        out["loss_last"] = losses[-1]

    # MFU from the header's compiled-FLOPs count at this phase's p50
    flops = header.get("flops_per_step")
    if flops and p50 > 0:
        n_chips = int(header.get("n_chips") or 1)
        achieved = float(flops) / p50 / n_chips
        out["achieved_tflops_per_chip"] = achieved / 1e12
        peak = header.get("peak_flops_per_chip")
        out["mfu"] = (achieved / float(peak)) if peak else None
    return out


def summarize_run(run_dir: str) -> dict[str, Any]:
    """Summarize one run directory.  Raises FileNotFoundError when there
    is nothing at all to report on — but a dir holding only serve.json /
    flight.json (a ``bench.py --serve`` run writes no metrics.jsonl:
    its per-token records live in serve.json) still summarizes."""
    from ddl25spring_tpu.obs.recorder import FLIGHT_BASENAME

    metrics_path = os.path.join(run_dir, "metrics.jsonl")
    try:
        records = read_jsonl(metrics_path)
    except FileNotFoundError:
        if not any(
            os.path.exists(os.path.join(run_dir, f))
            for f in (SERVE_BASENAME, FLIGHT_BASENAME)
        ):
            raise
        records = []
    # a run may append late header records for facts only known at the
    # end (compiled flops, measured link bandwidth): merge them in order
    header: dict[str, Any] = {}
    for r in records:
        if r.get("record") == "header":
            header.update({k: v for k, v in r.items() if v is not None})
    steps = [r for r in records if r.get("record") == "step"]

    phases: dict[str, list[dict]] = {}
    for r in steps:
        phases.setdefault(r.get("label", "run"), []).append(r)

    out: dict[str, Any] = {
        "run_dir": run_dir,
        "header": header,
        "phases": {k: _phase_summary(v, header) for k, v in phases.items()},
    }

    # GPipe bubble: analytic from the recorded schedule shape; measured
    # tick cadence alongside when the pipeline's tick counters fired
    S = header.get("num_stages")
    M = header.get("num_microbatches")
    cpath = os.path.join(run_dir, "counters.json")
    counters = None
    if os.path.exists(cpath):
        with open(cpath) as f:
            counters = json.load(f)
        statics = counters.get("static", {})
        # the instrumented pipeline records its own (S, M); use them when
        # the driver's header didn't carry the schedule shape
        S = S or statics.get("pipeline.num_stages")
        M = M or statics.get("pipeline.num_microbatches")
    if S and M:
        out["bubble_fraction"] = gpipe_bubble_fraction(S, M)
        out.setdefault("num_stages", S)
        out.setdefault("num_microbatches", M)
    if counters is not None:
        out["counters"] = counters
        ticks = counters.get("series", {}).get("pipeline.tick")
        if ticks and len(ticks) >= 3:
            # the callback fires once per mesh shard, so every tick index
            # arrives D times nearly simultaneously, and the index resets
            # to 0 on each new scan invocation (next step / bwd recompute).
            # Keep only the first arrival of each index and measure
            # consecutive-index transitions within one scan pass — the
            # raw diff's intra-tick gaps would swamp the median on D >= 3.
            dts = []
            prev_i = prev_t = None
            for i, t in ticks:
                if prev_i is not None and i == prev_i:
                    continue  # another shard's arrival for the same tick
                if prev_i is not None and i == prev_i + 1 and t > prev_t:
                    dts.append(t - prev_t)
                prev_i, prev_t = i, t
            if dts:
                out["tick_interval_s_p50"] = float(np.percentile(dts, 50))

    # the unified run timeline, when the run configured one
    # (ddl25spring_tpu/obs/timeline.py): event counts by kind, the
    # slowest requests with their TTFT decomposition, and which
    # requests rode through each elastic reshape window (membership by
    # virtual clock — comparable across deterministic A/B runs)
    from ddl25spring_tpu.obs.timeline import TIMELINE_BASENAME, read_timeline

    tlpath = os.path.join(run_dir, TIMELINE_BASENAME)
    if os.path.exists(tlpath):
        try:
            _, tl_events = read_timeline(run_dir)
            tl_counts: dict[str, int] = {}
            for e in tl_events:
                k = e.get("kind", "?")
                tl_counts[k] = tl_counts.get(k, 0) + 1
            firsts = [
                e for e in tl_events
                if e.get("kind") == "serve_first_token"
                and isinstance(e.get("ttft_s"), (int, float))
            ]
            slowest = [
                {
                    k: e.get(k)
                    for k in ("rid", "engine", "replica", "ttft_s",
                              "queue_wait_s", "prefill_s",
                              "first_decode_s", "vt_s")
                }
                for e in sorted(
                    firsts, key=lambda e: -e["ttft_s"])[:5]
            ]
            windows = []
            for end in tl_events:
                if end.get("kind") != "reshape_end":
                    continue
                t0, t1 = end.get("t"), end.get("t_end")
                members = sorted({
                    e["rid"] for e in tl_events
                    if "rid" in e
                    and e.get("engine") == end.get("engine")
                    and isinstance(e.get("vt_s"), (int, float))
                    and t0 is not None and t1 is not None
                    and t0 <= e["vt_s"] <= t1
                })
                windows.append({
                    "reason": end.get("reason"),
                    "t": t0,
                    "t_end": t1,
                    "old": end.get("old"),
                    "new": end.get("new"),
                    "requests": members,
                })
            out["timeline"] = {
                "events": len(tl_events),
                "counts": tl_counts,
                "slowest_requests": slowest,
                "reshape_windows": windows,
            }
        except (ValueError, json.JSONDecodeError, OSError) as e:
            # a torn line (killed mid-write) must not cost the rest
            out["timeline"] = {
                "error": f"unreadable {TIMELINE_BASENAME}: {e}"
            }

    tpath = os.path.join(run_dir, "trace.json")
    if os.path.exists(tpath):
        with open(tpath) as f:
            trace = json.load(f)
        evs = [
            e for e in trace.get("traceEvents", []) if e.get("ph") == "X"
        ]
        out["span_counts"] = {
            n: sum(1 for e in evs if e["name"] == n)
            for n in sorted({e["name"] for e in evs})
        }

    # runtime health, when a flight recorder dumped into this run dir
    # (ddl25spring_tpu/obs/recorder.py): sentinel violations, the last
    # step records, and — for stall dumps — the host thread stacks
    from ddl25spring_tpu.obs.recorder import FLIGHT_BASENAME

    fpath = os.path.join(run_dir, FLIGHT_BASENAME)
    if os.path.exists(fpath):
        try:
            with open(fpath) as f:
                fl = json.load(f)
            out["health"] = {
                "reason": fl.get("reason"),
                "recorded": fl.get("recorded"),
                "violations": fl.get("violations", 0),
                "last_violation": fl.get("last_violation"),
                "stall": fl.get("stall"),
                "thread_stacks": sorted(fl.get("thread_stacks", {})),
                "meta": fl.get("meta", {}),
                "last_records": (fl.get("records") or [])[-5:],
                "exception": fl.get("exception"),
            }
            # recovery facts (the ft/ layer): the flight meta carries
            # the durable-checkpoint annotations and the per-kind
            # counters carry save/restore traffic — enough to answer
            # "what survived" from the dump alone
            meta = fl.get("meta") or {}
            counts = fl.get("counts") or {}
            recovery = {
                k: meta[k]
                for k in (
                    "ckpt_dir",
                    "ckpt_last_durable_step",
                    "resumed_from_step",
                    "steps_replayed",
                )
                if meta.get(k) is not None
            }
            for kind, label in (
                ("save", "saves"),
                ("save_skipped", "saves_skipped"),
                ("restore", "restores"),
                ("chaos", "chaos_faults"),
                # elastic in-run reshapes (ft/elastic.py): RECOVERY
                # events, not violations — the health gate reports
                # them informationally and never fails on them
                ("reshape", "reshapes"),
            ):
                if counts.get(kind):
                    recovery[label] = counts[kind]
            if counts.get("reshape"):
                reshape_recs = [
                    r for r in fl.get("records") or []
                    if r.get("kind") == "reshape"
                ]
                if reshape_recs:
                    recovery["last_reshape"] = reshape_recs[-1]
            if recovery:
                out["recovery"] = recovery
        except (json.JSONDecodeError, OSError) as e:
            # a truncated dump must not cost the measured metrics
            out["health"] = {
                "error": f"unreadable {FLIGHT_BASENAME}: {e}"
            }

    # the autosave manifest (run_dir/ckpt by bench convention, or
    # wherever the flight meta points): the checkpoint layer's own
    # account of the last durable step — readable even when the crash
    # never managed a flight dump (ft.manifest is stdlib-only: the
    # post-mortem must work even where orbax itself is what broke)
    from ddl25spring_tpu.ft.manifest import read_manifest

    # the flight meta's recorded ckpt_dir is authoritative (a custom
    # --ckpt-dir run must not be shadowed by a stale manifest sitting
    # at the default location); the run_dir/ckpt convention is the
    # fallback for dumps that never got annotated
    rec_dir = (out.get("recovery") or {}).get("ckpt_dir")
    ckpt_dirs = ([rec_dir] if rec_dir else []) + [
        os.path.join(run_dir, "ckpt")
    ]
    for cd in ckpt_dirs:
        man = read_manifest(cd)
        if man is not None:
            rec = out.setdefault("recovery", {})
            rec["manifest"] = {
                k: man.get(k)
                for k in ("last_durable_step", "last_requested_step",
                          "save_every", "saves", "save_skipped")
            }
            rec.setdefault("ckpt_dir", cd)
            break

    # measured perf record, when a bench/perfscope run dropped one here
    # (ddl25spring_tpu/obs/perfscope.py): step-wall decomposition into
    # compute vs exposed comms, measured MFU against the calibrated
    # chip peak, and the projection error vs the compile-time roofline
    from ddl25spring_tpu.obs.perfscope import PERF_BASENAME

    ppath = os.path.join(run_dir, PERF_BASENAME)
    if os.path.exists(ppath):
        try:
            with open(ppath) as f:
                out["perf"] = json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            out["perf"] = {"error": f"unreadable {PERF_BASENAME}: {e}"}

    # serving record, when a `bench.py --serve` run dropped one here
    # (ddl25spring_tpu/serve/driver.py): admission counters, TTFT /
    # per-token latency percentiles, page-pool occupancy, and the
    # continuous-vs-static A/B — the Serving section below
    spath = os.path.join(run_dir, SERVE_BASENAME)
    if os.path.exists(spath):
        try:
            with open(spath) as f:
                sdoc = json.load(f)
            out["serve"] = {
                "key": sdoc.get("key"),
                "requests": sdoc.get("requests"),
                "ramp": sdoc.get("ramp"),
                "ab": sdoc.get("ab"),
                "prefix_ab": sdoc.get("prefix_ab"),
                "spec_ab": sdoc.get("spec_ab"),
                "tp_ab": sdoc.get("tp_ab"),
                "reshape": sdoc.get("reshape"),
                "git_sha": sdoc.get("git_sha"),
            }
        except (json.JSONDecodeError, OSError) as e:
            out["serve"] = {"error": f"unreadable {SERVE_BASENAME}: {e}"}

    # runtime memory record, when a memscope-wired run dropped one here
    # (ddl25spring_tpu/obs/memscope.py): live-bytes/RSS high-water vs
    # the accounted budget, pool telemetry, leak + growth verdicts —
    # the Memory section below, gated by tools/mem_report.py --check
    from ddl25spring_tpu.obs.memscope import MEM_BASENAME

    mpath = os.path.join(run_dir, MEM_BASENAME)
    if os.path.exists(mpath):
        try:
            with open(mpath) as f:
                out["mem"] = json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            out["mem"] = {"error": f"unreadable {MEM_BASENAME}: {e}"}

    # goodput decomposition, when a graft-goodput run/lineage dropped
    # one here (ddl25spring_tpu/obs/goodput.py): the badput taxonomy,
    # the sum-to-wall contract, and — for serve scopes — SLO attainment
    # and availability; trend/gate with tools/goodput_report.py
    from ddl25spring_tpu.obs.goodput import (
        GOODPUT_BASENAME,
        read_run_goodput,
    )

    if os.path.exists(os.path.join(run_dir, GOODPUT_BASENAME)):
        gp = read_run_goodput(run_dir)
        out["goodput"] = (
            gp if isinstance(gp, dict) and gp.get("record") == "goodput"
            else {"error": f"unreadable {GOODPUT_BASENAME}"}
        )

    # compile-time analytics, when a bench/CLI run dropped its report here
    # (ddl25spring_tpu/obs/compile_report.py) — measured p50/p95 above,
    # compiled collectives/HBM/MFU-projection below, one run dir
    from ddl25spring_tpu.obs.compile_report import COMPILE_REPORT_BASENAME

    crpath = os.path.join(run_dir, COMPILE_REPORT_BASENAME)
    if os.path.exists(crpath):
        try:
            with open(crpath) as f:
                out["compile_report"] = json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            # a truncated report (killed mid-write) must not cost the
            # measured runtime metrics in the same run dir
            out["compile_report"] = {
                "error": f"unreadable {COMPILE_REPORT_BASENAME}: {e}"
            }
    return out


def format_report(summary: dict[str, Any]) -> str:
    """Render the summary as the aligned table the CLI prints."""
    h = summary.get("header", {})
    lines = [f"run: {summary['run_dir']}"]
    meta_bits = []
    for k in ("layout", "topology", "git_sha", "jax_version"):
        if h.get(k):
            v = h[k]
            meta_bits.append(f"{k}={str(v)[:12] if k == 'git_sha' else v}")
    if h.get("mesh"):
        meta_bits.append(f"mesh={h['mesh']}")
    if h.get("device"):
        d = h["device"]
        meta_bits.append(f"device={d.get('kind') or d.get('platform')}")
    if meta_bits:
        lines.append("  " + "  ".join(meta_bits))
    lines.append("")

    def fmt(v, unit="", nd=2):
        if v is None:
            return "n/a"
        return f"{v:.{nd}f}{unit}"

    cols = (
        f"{'phase':<24}{'steps':>6}{'step p50':>12}{'step p95':>12}"
        f"{'steps/s p50':>13}{'samp/s/chip':>13}{'MFU':>8}"
    )
    lines.append(cols)
    lines.append("-" * len(cols))
    for name, ph in summary.get("phases", {}).items():
        lines.append(
            f"{name:<24}{ph.get('steps', 0):>6}"
            f"{fmt(ph.get('step_s_p50'), ' s', 4):>12}"
            f"{fmt(ph.get('step_s_p95'), ' s', 4):>12}"
            f"{fmt(ph.get('steps_per_sec_p50'), '', 2):>13}"
            f"{fmt(ph.get('samples_per_sec_per_chip_p50'), '', 1):>13}"
            f"{fmt(ph.get('mfu'), '', 4):>8}"
        )
    lines.append("")

    bf = summary.get("bubble_fraction")
    S = summary.get("num_stages") or h.get("num_stages")
    M = summary.get("num_microbatches") or h.get("num_microbatches")
    if bf is not None:
        lines.append(
            f"pipeline bubble fraction: {bf:.4f} "
            f"(GPipe (S-1)/(M+S-1) at S={S}, M={M})"
        )
    else:
        lines.append("pipeline bubble fraction: 0.0000 (no pipeline axis)")
    if summary.get("tick_interval_s_p50") is not None:
        lines.append(
            f"measured tick interval p50: "
            f"{summary['tick_interval_s_p50'] * 1e3:.2f} ms"
        )
    if h.get("h2d_mib_per_s"):
        lines.append(f"host->device link: {h['h2d_mib_per_s']:.1f} MiB/s")

    for name, ph in summary.get("phases", {}).items():
        if ph.get("achieved_tflops_per_chip") is not None:
            lines.append(
                f"achieved TFLOP/s/chip ({name}): "
                f"{ph['achieved_tflops_per_chip']:.2f}"
                + (
                    ""
                    if ph.get("mfu") is not None
                    else "  (no chip peak in the run header — not even "
                         "the calibrated cpu-host one; MFU n/a)"
                )
            )
            break

    p = summary.get("perf")
    if p:
        lines.append("")
        lines.append(
            "performance (perf.json — measured, not projected; "
            "see tools/perf_report.py for the cross-run trend):"
        )
        if p.get("error"):
            lines.append(f"  {p['error']}")
        else:
            def pms(key):
                v = p.get(key)
                return f"{v * 1e3:.3f} ms" if v is not None else "n/a"

            lines.append(
                f"  step p50 {pms('step_s_p50')}  p95 {pms('step_s_p95')}"
                f"  compute-only {pms('compute_s_p50')}"
                f"  exposed comms {pms('exposed_comms_s')}"
            )
            peak = p.get("peak_flops_per_chip")
            mm = p.get("measured_mfu")
            pm = p.get("projected_mfu")
            pe = p.get("projection_err")
            lines.append(
                "  measured MFU "
                + (f"{mm:.4f}" if mm is not None else "n/a")
                + (f" (chip {p.get('chip')}, peak "
                   f"{peak / 1e12:.2f} TFLOP/s {p.get('peak_source')})"
                   if peak else "")
                + (f"  projected {pm:.4f}"
                   f" [{p.get('projected_bound')}-bound]"
                   if pm is not None else "")
                + (f"  err {pe * 100:+.1f}%" if pe is not None else "")
            )
            eff = p.get("overlap_eff")
            n_sites = len(p.get("micro") or [])
            lines.append(
                "  overlap efficiency "
                + (f"{eff:.3f}" if eff is not None
                   else "n/a (no costed collectives)")
                + f"  (micro comms total {pms('micro_total_s')}"
                + f" over {n_sites} inventory site(s))"
            )

    sv = summary.get("serve")
    if sv:
        lines.append("")
        lines.append(
            "serving (serve.json — bench.py --serve; trend/gate with "
            "tools/serve_report.py):"
        )
        if sv.get("error"):
            lines.append(f"  {sv['error']}")
        else:
            ramp = sv.get("ramp") or {}
            key = sv.get("key") or {}
            if key:
                lines.append(
                    "  " + "  ".join(f"{k}={key[k]}" for k in sorted(key))
                )

            def sms(v):
                return f"{v * 1e3:.2f} ms" if isinstance(
                    v, (int, float)) else "n/a"

            lines.append(
                f"  requests {sv.get('requests')}  admitted "
                f"{ramp.get('admitted')}  rejected {ramp.get('rejected')}"
                f" {ramp.get('rejected_by_reason') or {}}  completed "
                f"{ramp.get('completed')}"
            )
            tps = ramp.get("tokens_per_sec_per_chip")
            lines.append(
                "  tokens/sec/chip "
                + (f"{tps:.2f}" if isinstance(tps, (int, float)) else "n/a")
                + f"  TTFT p50 {sms(ramp.get('ttft_s_p50'))} p95 "
                f"{sms(ramp.get('ttft_s_p95'))}"
                f"  per-token p50 {sms(ramp.get('tok_latency_s_p50'))} "
                f"p95 {sms(ramp.get('tok_latency_s_p95'))}"
            )
            dec = ramp.get("ttft_decomp")
            if dec and dec.get("requests"):
                lines.append(
                    f"  TTFT decomposition ({dec.get('clock')} clock, "
                    f"{dec['requests']} req): queue-wait p50 "
                    f"{sms(dec.get('queue_wait_s_p50'))} p95 "
                    f"{sms(dec.get('queue_wait_s_p95'))}  prefill p50 "
                    f"{sms(dec.get('prefill_s_p50'))} p95 "
                    f"{sms(dec.get('prefill_s_p95'))}  first-decode "
                    f"p50 {sms(dec.get('first_decode_s_p50'))} p95 "
                    f"{sms(dec.get('first_decode_s_p95'))}"
                )
            occ = ramp.get("page_pool_peak_occupancy")
            # occupancy in PER-CHIP bytes, not just global page counts:
            # under tp the page count is unchanged (pages are a global
            # logical resource) while each chip holds 1/tp of every
            # page's head dim — counts alone would read as if sharding
            # shrank nothing
            pool_pc = ramp.get("pool_bytes_per_chip")
            lines.append(
                f"  page pool peak {ramp.get('page_pool_peak_pages')}"
                f"/{ramp.get('page_pool_pages')} pages"
                + (f" ({occ * 100:.1f}%)" if isinstance(
                    occ, (int, float)) else "")
                + (f"  {pool_pc / 1024:.1f} KiB/chip" if isinstance(
                    pool_pc, (int, float)) else "")
                + f"  queue depth max {ramp.get('queue_depth_max')}"
                + f"  pool-ok failures {ramp.get('pool_ok_failures')}"
            )
            tp = ramp.get("tp")
            if isinstance(tp, int) and tp > 1:
                param_pc = ramp.get("param_bytes_per_chip")
                lines.append(
                    f"  tp {tp}"
                    + (" (weight streaming)" if ramp.get("weight_stream")
                       else "")
                    + (f"  params {param_pc / 1024:.1f} KiB/chip"
                       if isinstance(param_pc, (int, float)) else "")
                )
            prefix = ramp.get("prefix") or {}
            if prefix.get("enabled"):
                hit = ramp.get("prefix_hit_rate")
                lines.append(
                    "  prefix cache hit rate "
                    + (f"{hit * 100:.1f}%" if isinstance(
                        hit, (int, float)) else "n/a")
                    + f"  prefill saved {ramp.get('prefill_tokens_saved')}"
                    f" tokens / {ramp.get('prefill_flops_saved')} FLOPs"
                    f"  cached pages {prefix.get('cached_pages')}"
                    f"  evictions {prefix.get('evictions')}"
                )
            ab = sv.get("ab")
            if ab:
                lines.append(
                    "  A/B continuous "
                    f"{ab.get('continuous_tokens_at_budget')} vs static "
                    f"{ab.get('static_tokens_at_budget')} tokens at "
                    f"budget {ab.get('budget_s')} s  (advantage "
                    f"{ab.get('advantage_tokens')})"
                )
            pab = sv.get("prefix_ab")
            if pab:
                lines.append(
                    "  prefix A/B cached "
                    f"{pab.get('cached_tokens_at_budget')} vs cold "
                    f"{pab.get('cold_tokens_at_budget')} tokens at "
                    f"budget {pab.get('budget_s')} s  (advantage "
                    f"{pab.get('advantage_tokens')}, tokens match "
                    f"{pab.get('tokens_match')})"
                )
            spec = ramp.get("spec") or {}
            if spec.get("enabled"):
                acc = ramp.get("acceptance_rate")
                lines.append(
                    f"  speculative decode k={spec.get('k')} drafter "
                    f"{spec.get('draft_layers')}L: acceptance "
                    + (f"{acc * 100:.1f}%" if isinstance(
                        acc, (int, float)) else "n/a")
                    + f" ({ramp.get('draft_tokens_accepted')} acc / "
                    f"{ramp.get('draft_tokens_rejected')} rej)  "
                    f"rounds {spec.get('rounds')}  draft steps "
                    f"{spec.get('draft_steps')}  verify steps "
                    f"{spec.get('verify_steps')}"
                )
            sab = sv.get("spec_ab")
            if sab:
                lines.append(
                    "  spec A/B spec "
                    f"{sab.get('spec_tokens_at_budget')} vs non-spec "
                    f"{sab.get('nospec_tokens_at_budget')} tokens at "
                    f"budget {sab.get('budget_s')} s  (advantage "
                    f"{sab.get('advantage_tokens')}, tokens match "
                    f"{sab.get('tokens_match')})"
                )
            tab = sv.get("tp_ab")
            if tab:
                # ledger cells flatten the arms; the raw serve.json
                # record nests them under sharded/dense — accept both
                shard_b = tab.get("tp_mem_budget_bytes_per_chip")
                if shard_b is None:
                    shard_b = (tab.get("sharded") or {}).get(
                        "mem_budget_bytes_per_chip")
                dense_b = tab.get("dense_mem_budget_bytes_per_chip")
                if dense_b is None:
                    dense_b = (tab.get("dense") or {}).get(
                        "mem_budget_bytes_per_chip")
                lines.append(
                    f"  tp A/B (tp={tab.get('tp')}) sharded "
                    f"{tab.get('tp_tokens_at_budget')} vs dense "
                    f"{tab.get('dense_tokens_at_budget')} tokens at "
                    f"budget {tab.get('budget_s')} s  (tokens match "
                    f"{tab.get('tokens_match')}, per-chip "
                    + (f"{shard_b / 1024:.1f}" if isinstance(
                        shard_b, (int, float)) else "n/a")
                    + " vs "
                    + (f"{dense_b / 1024:.1f} KiB" if isinstance(
                        dense_b, (int, float)) else "n/a")
                    + f", shrunk {tab.get('budget_shrunk')})"
                )
            rsh = sv.get("reshape")
            if rsh:
                evs = rsh.get("events") or []
                p95r = rsh.get("ttft_s_p95_reshape")
                p95s = rsh.get("ttft_s_p95_steady")
                lines.append(
                    f"  elastic reshape: {len(evs)} event(s) "
                    + " ".join(
                        f"[{e.get('reason')} {e.get('old')}->"
                        f"{e.get('new')}]" for e in evs
                    )
                    + f"  dropped {rsh.get('dropped_requests')}"
                    + f"  TTFT p95 window {sms(p95r)} vs steady "
                    f"{sms(p95s)}"
                )

    mem = summary.get("mem")
    if mem:
        lines.append("")
        lines.append(
            "memory (mem.json — graft-mem runtime observatory; gate "
            "with tools/mem_report.py --check):"
        )
        if mem.get("error"):
            lines.append(f"  {mem['error']}")
        else:
            def mib(v):
                return (
                    f"{v / (1 << 20):.1f} MiB"
                    if isinstance(v, (int, float)) else "n/a"
                )

            scope = mem.get("memscope") or {}
            lines.append(
                f"  live bytes peak {mib(scope.get('live_bytes_peak'))}"
                f"  host RSS peak {mib(scope.get('rss_bytes_peak'))}"
                f"  samples {scope.get('samples')}"
            )
            b = mem.get("budget") or {}
            if b.get("available"):
                lines.append(
                    f"  budget ({b.get('source')}) "
                    f"{mib(b.get('budget_bytes'))}  measured/budget "
                    f"{b.get('ratio')}  within band "
                    f"(tol {b.get('tolerance')}): {b.get('within_band')}"
                )
            pool = mem.get("pool")
            if pool:
                lines.append(
                    f"  kv pool {pool.get('used_pages')}"
                    f"/{pool.get('n_pages')} pages used "
                    f"(cache-held {pool.get('cache_held_pages')}, "
                    f"table-held {pool.get('table_held_pages')})  "
                    f"fragmentation {pool.get('fragmentation')}"
                )
            lines.append(
                f"  leaked pages {mem.get('leaked_pages', 0)}  "
                f"growth violations {mem.get('growth_violations', 0)}"
                + (
                    f"  reshape step-downs "
                    f"{len(mem.get('reshape_steps') or [])}"
                    if mem.get("reshape_steps") is not None else ""
                )
            )

    gp = summary.get("goodput")
    if gp:
        lines.append("")
        lines.append(
            "goodput (goodput.json — graft-goodput lineage "
            "decomposition; trend/gate with tools/goodput_report.py):"
        )
        if gp.get("error"):
            lines.append(f"  {gp['error']}")
        else:
            total = gp.get("total_wall_s")
            fu = gp.get("fraction_useful")
            lines.append(
                f"  scope {gp.get('scope')}  lineage "
                f"{gp.get('lineage_id')}  attempts "
                f"{gp.get('attempts') or gp.get('attempt') or 1}  "
                f"chips {gp.get('chips')}  wall "
                + (f"{total:.3f} s" if isinstance(total, (int, float))
                   else "n/a")
            )
            seconds = gp.get("seconds") or {}
            if seconds and isinstance(total, (int, float)) and total > 0:
                for bucket, secs in sorted(
                        seconds.items(), key=lambda kv: -kv[1]):
                    if not secs:
                        continue
                    lines.append(
                        f"  {bucket:<18} {secs:>9.3f} s "
                        f"({secs / total * 100:5.1f}%)"
                    )
            sc = gp.get("sum_check") or {}
            lines.append(
                "  fraction useful "
                + (f"{fu:.4f}" if isinstance(fu, (int, float))
                   else "n/a")
                + f"  replayed steps {gp.get('replayed_steps_count', 0)}"
                + f"  sum-to-wall ok: {sc.get('ok')}"
            )
            if gp.get("scope") == "serve":
                att = gp.get("slo_attainment")
                avail = gp.get("availability")
                gtps = gp.get("goodput_tokens_per_sec_per_chip")
                slo = gp.get("slo") or {}
                lines.append(
                    "  SLO attainment "
                    + (f"{att * 100:.1f}%" if isinstance(
                        att, (int, float)) else "n/a")
                    + (f" (TTFT<={slo.get('ttft_ms')}ms, "
                       f"tok<={slo.get('tok_ms')}ms, "
                       f"{slo.get('clock')} clock)" if slo else "")
                    + "  availability "
                    + (f"{avail * 100:.1f}%" if isinstance(
                        avail, (int, float)) else "n/a")
                    + "  goodput tok/s/chip "
                    + (f"{gtps:.2f}" if isinstance(
                        gtps, (int, float)) else "n/a")
                )

    c = summary.get("counters", {})
    statics = c.get("static", {})
    scalars = c.get("scalars", {})
    if statics or scalars:
        lines.append("")
        lines.append("counters:")
        for k, v in sorted(statics.items()):
            lines.append(f"  {k:<40} {v}")
        for k, s in sorted(scalars.items()):
            lines.append(
                f"  {k:<40} count={int(s['count'])} mean={s['mean']:.6g} "
                f"last={s.get('last', float('nan')):.6g}"
            )
    if summary.get("span_counts"):
        lines.append("")
        lines.append("host spans (trace.json — load in Perfetto):")
        for n, cnt in summary["span_counts"].items():
            lines.append(f"  {n:<40} x{cnt}")

    tl = summary.get("timeline")
    if tl:
        lines.append("")
        lines.append(
            "timeline (timeline.jsonl — merge with "
            "tools/trace_export.py):"
        )
        if tl.get("error"):
            lines.append(f"  {tl['error']}")
        else:
            lines.append(
                f"  {tl.get('events', 0)} event(s): "
                + "  ".join(
                    f"{k}x{v}" for k, v in sorted(
                        (tl.get("counts") or {}).items())
                )
            )

            def tms(v):
                return f"{v * 1e3:.2f} ms" if isinstance(
                    v, (int, float)) else "n/a"

            if tl.get("slowest_requests"):
                lines.append("  slowest requests (TTFT = queue-wait + "
                             "prefill + first-decode):")
                for r in tl["slowest_requests"]:
                    lines.append(
                        f"    rid={r.get('rid')} "
                        f"[{r.get('engine')}:r{r.get('replica')}] "
                        f"TTFT {tms(r.get('ttft_s'))} = "
                        f"queue {tms(r.get('queue_wait_s'))} + "
                        f"prefill {tms(r.get('prefill_s'))} + "
                        f"first-decode {tms(r.get('first_decode_s'))}"
                    )
            for w in tl.get("reshape_windows") or []:
                reqs = w.get("requests") or []
                lines.append(
                    f"  reshape window [{w.get('reason')} "
                    f"{w.get('old')}->{w.get('new')}] vt "
                    f"{w.get('t')}..{w.get('t_end')} s: "
                    f"{len(reqs)} request(s) in flight "
                    f"{reqs[:10]}{'...' if len(reqs) > 10 else ''}"
                )

    h = summary.get("health")
    if h:
        lines.append("")
        lines.append("health (flight.json — the crash-surviving ring):")
        if h.get("error"):
            lines.append(f"  {h['error']}")
        else:
            lines.append(
                f"  dump reason: {h.get('reason')}  records: "
                f"{h.get('recorded')}  sentinel violations: "
                f"{h.get('violations', 0)}"
            )
            lv = h.get("last_violation")
            if lv:
                lines.append(
                    f"  last violation: strategy={lv.get('strategy')} "
                    f"step={lv.get('step')} "
                    f"metric={lv.get('violating_metric')} "
                    f"leaves={lv.get('nonfinite_leaves', [])}"
                )
            st = h.get("stall")
            if st:
                lines.append(
                    f"  STALL: watchdog={st.get('watchdog')} idle "
                    f"{st.get('idle_s')}s past deadline "
                    f"{st.get('deadline_s')}s — "
                    f"{len(h.get('thread_stacks', []))} host thread "
                    "stacks in the dump"
                )
            if h.get("exception"):
                lines.append(f"  died on: {h['exception']}")
            for r in h.get("last_records", []):
                bits = "  ".join(
                    f"{k}={r[k]}"
                    for k in ("strategy", "step", "loss", "grad_norm",
                              "wall_s", "violating_metric")
                    if k in r
                )
                lines.append(f"  [{r.get('kind', 'step')}] {bits}")

    rec = summary.get("recovery")
    if rec:
        lines.append("")
        lines.append("recovery (ft/ autosave + flight meta — what survived):")
        man = rec.get("manifest") or {}
        durable = rec.get("ckpt_last_durable_step",
                          man.get("last_durable_step"))
        bits = [f"last durable step: {durable}"]
        if rec.get("ckpt_dir"):
            bits.append(f"ckpt: {rec['ckpt_dir']}")
        lines.append("  " + "  ".join(bits))
        if rec.get("resumed_from_step") is not None:
            replay = rec.get("steps_replayed")
            lines.append(
                f"  resumed from step {rec['resumed_from_step']}"
                + (f"  ({replay} step(s) replayed)"
                   if replay is not None else "")
            )
        if rec.get("reshapes"):
            last = rec.get("last_reshape") or {}
            lines.append(
                f"  elastic reshapes: {rec['reshapes']} (recovery "
                "events, not violations)"
                + (
                    f"  last: {last.get('old')} -> {last.get('new')} "
                    f"({last.get('reason')}, "
                    f"{last.get('steps_lost')} step(s) lost, "
                    f"{last.get('wall_s')} s)"
                    if last else ""
                )
            )
        counts_bits = [
            f"{k}={rec[k]}"
            for k in ("saves", "saves_skipped", "restores", "chaos_faults")
            if rec.get(k) is not None
        ]
        if man.get("save_skipped"):
            counts_bits.append(
                f"manifest save_skipped={man['save_skipped']} "
                "(poisoned-checkpoint gate)"
            )
        if counts_bits:
            lines.append("  " + "  ".join(counts_bits))

    cr = summary.get("compile_report")
    if cr:
        lines.append("")
        lines.append(
            "compile analytics (compile_report.json — no device needed; "
            "see tools/comms_report.py):"
        )
        if cr.get("error"):
            lines.append(f"  {cr['error']}")
        for name, r in cr.get("strategies", {}).items():
            if "error" in r:
                lines.append(f"  {name:<14} FAILED: {str(r['error'])[:90]}")
                continue
            totals = r.get("collectives", {}).get("totals", {})
            coll = "  ".join(
                f"{k} x{t['count']} ({t['result_bytes'] / 1024:.1f} KiB)"
                for k, t in sorted(totals.items())
            ) or "no collectives"
            lines.append(f"  {name:<14} {coll}")
            mem = r.get("memory") or {}
            proj = (r.get("projection") or {}).get("TPU v4")
            bits = []
            if mem.get("peak_hbm_bytes") is not None:
                bits.append(
                    f"peak HBM est {mem['peak_hbm_bytes'] / 2**20:.1f} MiB"
                )
            if r.get("flops"):
                bits.append(f"flops/step {r['flops']:.3g}")
            if proj:
                bits.append(
                    f"projected MFU(v4) {proj['projected_mfu']:.3f} "
                    f"[{proj['bound']}-bound]"
                )
            if bits:
                lines.append(f"  {'':<14} {'  '.join(bits)}")
            viols = r.get("signature_violations")
            if viols:
                for v in viols:
                    lines.append(f"  {'':<14} VIOLATION: {v}")
    return "\n".join(lines)
