"""MNIST data pipeline.

The reference pulls MNIST through torchvision with download
(``lab/tutorial_1a/hfl_complete.py:26-31``) and normalizes with the canonical
(0.1307, 0.3081) train statistics.  This build runs in a zero-egress
environment, so the loader has two paths:

1. real MNIST from raw IDX files if present (``DDL25_MNIST_DIR`` env var or
   ``./data/mnist``) — same bytes torchvision would download;
2. a deterministic synthetic MNIST-like dataset (class-prototype + noise)
   with identical shapes/dtypes, sufficient for every equivalence and
   convergence test in the suite.  Golden accuracy tables from
   ``lab/series01.ipynb`` are only reproducible with real data.

Arrays are NHWC ``float32`` ``[N, 28, 28, 1]``, normalized like the reference.
"""

from __future__ import annotations

import gzip
import os
import struct
from functools import lru_cache
from pathlib import Path

import numpy as np

MEAN, STD = 0.1307, 0.3081


def _norm(x: np.ndarray) -> np.ndarray:
    """[N,28,28] in [0,1] -> normalized NHWC float32 (reference constants)."""
    return ((x - MEAN) / STD)[..., None].astype(np.float32)


def _read_idx(path: Path) -> np.ndarray:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


def _find_idx_dir() -> Path | None:
    for cand in (os.environ.get("DDL25_MNIST_DIR"), "data/mnist", "data/MNIST/raw"):
        if cand and Path(cand).exists():
            d = Path(cand)
            for stem in ("train-images-idx3-ubyte", "train-images.idx3-ubyte"):
                if (d / stem).exists() or (d / (stem + ".gz")).exists():
                    return d
    return None


def _synthetic(n: int, seed: int, noise: float = 0.25) -> tuple[np.ndarray, np.ndarray]:
    """Class-prototype images + per-sample amplitude jitter + gaussian noise:
    learnable to high accuracy by a CNN, fully deterministic.  The prototypes
    are blocky (4x4 upsampled) so convolutions have local structure to find.
    """
    # class structure is FIXED (independent of `seed`) so train/test splits
    # sample from the same distribution; `seed` only drives the sampling
    proto_rng = np.random.default_rng(777)
    coarse = (proto_rng.random((10, 7, 7)) < 0.35).astype(np.float32)
    protos = np.kron(coarse, np.ones((4, 4), np.float32))  # [10, 28, 28]
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    scale = rng.uniform(0.6, 1.0, size=(n, 1, 1)).astype(np.float32)
    imgs = protos[labels] * scale + rng.normal(0.0, noise, (n, 28, 28)).astype(
        np.float32
    )
    imgs = np.clip(imgs, 0.0, 1.0)
    return imgs.astype(np.float32), labels


@lru_cache(maxsize=1)
def load_digits_28x28(
    n_train: int = 1437, n_test: int = 360, seed: int = 0
) -> dict[str, np.ndarray]:
    """REAL handwritten-digit data with MNIST shapes, zero egress.

    sklearn ships the UCI Optical-Recognition-of-Handwritten-Digits set
    (1,797 8x8 images) inside the package, so this is genuine handwritten
    pixel data available on the image: upsampled 8x8 -> 24x24 (x3 kron)
    and zero-padded to 28x28, scaled to [0,1], normalized with the same
    constants as :func:`load_mnist` so it drops into every MNIST consumer
    (MnistCnn, the FL servers, the sweep harness).

    Purpose: the synthetic prototype set saturates every FL config at
    ~100% (RESULTS.md §2), hiding the FedSGD-vs-FedAvg separation the
    homework sweeps exist to show; on this real data the separation and
    the non-IID trends manifest.  The golden `series01.ipynb` tables
    remain pinned to true MNIST (``DDL25_MNIST_DIR``) — different
    dataset, different absolute numbers.
    """
    from sklearn.datasets import load_digits

    d = load_digits()
    imgs = (d.images.astype(np.float32) / 16.0).clip(0.0, 1.0)
    up = np.kron(imgs, np.ones((3, 3), np.float32))  # [N, 24, 24]
    up = np.pad(up, ((0, 0), (2, 2), (2, 2)))
    labels = d.target.astype(np.int32)
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(up))
    up, labels = up[order], labels[order]
    if n_train + n_test > len(up):
        raise ValueError(
            f"digits has {len(up)} samples < {n_train}+{n_test} requested"
        )
    return {
        "x_train": _norm(up[:n_train]),
        "y_train": labels[:n_train],
        "x_test": _norm(up[n_train:n_train + n_test]),
        "y_test": labels[n_train:n_train + n_test],
    }


@lru_cache(maxsize=1)
def load_mnist(
    n_train: int = 60_000, n_test: int = 10_000, seed: int = 0
) -> dict[str, np.ndarray]:
    """Return ``{"x_train","y_train","x_test","y_test"}`` normalized NHWC."""
    d = _find_idx_dir()
    if d is not None:
        def grab(stem_img, stem_lbl):
            def first(*names):
                for nm in names:
                    for suf in ("", ".gz"):
                        p = d / (nm + suf)
                        if p.exists():
                            return p
                raise FileNotFoundError(nm)

            x = _read_idx(first(stem_img, stem_img.replace("-idx", ".idx")))
            y = _read_idx(first(stem_lbl, stem_lbl.replace("-idx", ".idx")))
            return x.astype(np.float32) / 255.0, y.astype(np.int32)

        x_tr, y_tr = grab("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
        x_te, y_te = grab("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")
    else:
        x_tr, y_tr = _synthetic(n_train, seed)
        x_te, y_te = _synthetic(n_test, seed + 1)

    return {
        "x_train": _norm(x_tr[:n_train]),
        "y_train": y_tr[:n_train],
        "x_test": _norm(x_te[:n_test]),
        "y_test": y_te[:n_test],
    }
