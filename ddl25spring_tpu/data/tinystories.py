"""TinyStories-style token stream.

The reference streams TinyStories through simplellm's loader:
``TinyStories(tokenizer, batch_size, seq_l, skip=rank*3000)`` yielding
``(B, L)`` token batches, with ``skip`` used to give DP ranks disjoint data
(``lab/tutorial_1b/DP/gradient_aggr/intro_DP_GA.py:29``).  This build keeps
that iterator contract.  Sources, in order:

1. a local text corpus (``DDL25_TINYSTORIES_TXT`` env var, or
   ``data/tinystories.txt``) — one story per ``<|endoftext|>``-separated
   block, as in the public dataset dump;
2. an offline deterministic story generator (template grammar over small
   word lists) — statistically simple enough that a small LLaMA's loss
   visibly falls, which is all the reference's convergence-by-eyeball
   verification observes (``out<rank>.txt`` prints, SURVEY §4).
"""

from __future__ import annotations

import os
from pathlib import Path

import numpy as np

_NAMES = ["Tom", "Lily", "Max", "Anna", "Ben", "Mia", "Sam", "Zoe"]
_ANIMALS = ["cat", "dog", "bird", "fox", "frog", "mouse", "bear", "duck"]
_OBJECTS = ["ball", "box", "kite", "cake", "hat", "boat", "drum", "book"]
_PLACES = ["park", "house", "garden", "forest", "beach", "school"]
_VERBS = ["found", "liked", "saw", "took", "made", "lost", "shared", "hid"]
_ADJ = ["red", "big", "small", "shiny", "soft", "funny", "old", "new"]


def generate_story(rng: np.random.Generator) -> str:
    n, a = rng.choice(_NAMES), rng.choice(_ANIMALS)
    o, p = rng.choice(_OBJECTS), rng.choice(_PLACES)
    v, adj = rng.choice(_VERBS), rng.choice(_ADJ)
    v2, o2 = rng.choice(_VERBS), rng.choice(_OBJECTS)
    return (
        f"One day {n} went to the {p}. {n} {v} a {adj} {o}. "
        f"A {a} came to play. The {a} {v2} the {o2}. "
        f"{n} and the {a} were happy. They played all day. The end."
    )


def _load_corpus(seed: int, min_chars: int) -> list[str]:
    for cand in (os.environ.get("DDL25_TINYSTORIES_TXT"), "data/tinystories.txt"):
        if cand and Path(cand).exists():
            text = Path(cand).read_text(errors="replace")
            stories = [s.strip() for s in text.split("<|endoftext|>") if s.strip()]
            if stories:
                return stories
    rng = np.random.default_rng(seed)
    stories, total = [], 0
    while total < min_chars:
        s = generate_story(rng)
        stories.append(s)
        total += len(s)
    return stories


class TinyStories:
    """Iterator over ``(batch_size, seq_l)`` int32 token batches.

    API parity with simplellm's loader: ``TinyStories(tokenizer, batch_size,
    seq_l, skip=...)``; ``skip`` drops that many *samples* from the head of
    the stream so DP replicas draw disjoint data.
    """

    def __init__(
        self,
        tokenizer,
        batch_size: int = 3,
        seq_l: int = 256,
        skip: int = 0,
        seed: int = 0,
        min_chars: int = 2_000_000,
    ):
        self.tokenizer = tokenizer
        self.batch_size = batch_size
        self.seq_l = seq_l
        self.skip = skip
        stories = _load_corpus(seed, min_chars)
        ids: list[int] = []
        for s in stories:
            ids.extend(tokenizer.encode(s))
            ids.append(tokenizer.eos_id)
        self._stream = np.asarray(ids, dtype=np.int32)

    def __iter__(self):
        tok_per_sample = self.seq_l
        n_samples = len(self._stream) // tok_per_sample
        if n_samples < 1:
            raise ValueError(
                f"corpus too small: {len(self._stream)} tokens < seq_l={self.seq_l}"
            )
        i = self.skip
        while True:
            # modular indexing: always a full batch, any skip value valid
            # (infinite wrap-around stream, like the reference's)
            idx = np.arange(i, i + self.batch_size) % n_samples
            batch = np.stack(
                [
                    self._stream[j * tok_per_sample : (j + 1) * tok_per_sample]
                    for j in idx
                ]
            )
            i = (i + self.batch_size) % n_samples
            yield batch
