from ddl25spring_tpu.data.mnist import load_mnist
from ddl25spring_tpu.data.splitter import split_indices

__all__ = ["load_mnist", "split_indices"]
