"""ctypes binding for the native C++ CIFAR-10 loader/prefetcher.

The compute path is JAX/XLA; the input pipeline around it is native C++
(``native/dataloader.cc``): parsing, per-epoch shuffling, normalization, and
batch assembly run in worker threads that prefetch ahead of the TPU step
loop.  This module builds the shared library on first use (``make -C
native``) and exposes a Python iterator; callers that can tolerate the slow
path should catch ``NativeLoaderUnavailable`` and fall back to
:func:`ddl25spring_tpu.data.cifar10.load_cifar10`'s in-memory arrays.
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_NAME = "libddl25_dataloader.so"
_lock = threading.Lock()
_lib = None


class NativeLoaderUnavailable(RuntimeError):
    """Toolchain or data missing — use the numpy path instead."""


def load_native_lib(lib_name: str) -> ctypes.CDLL:
    """Build-on-demand + load for a ``native/`` shared library: shared by
    the C++ dataloader and BPE bindings so the make/CDLL/error handling
    lives once.  Raises :class:`NativeLoaderUnavailable` when the
    toolchain or artifact is unusable (callers fall back to Python)."""
    so = _NATIVE_DIR / lib_name
    if not so.exists():
        try:
            subprocess.run(
                ["make", "-C", str(_NATIVE_DIR), lib_name],
                check=True, capture_output=True, text=True,
            )
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            raise NativeLoaderUnavailable(
                f"building {lib_name} failed: {detail}"
            ) from e
    try:
        return ctypes.CDLL(str(so))
    except OSError as e:  # wrong arch / corrupt .so: fall back, don't crash
        raise NativeLoaderUnavailable(f"loading {so} failed: {e}") from e


def _load_lib():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        lib = load_native_lib(_LIB_NAME)
        lib.dl_create.restype = ctypes.c_void_p
        lib.dl_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.dl_error.restype = ctypes.c_char_p
        lib.dl_error.argtypes = [ctypes.c_void_p]
        lib.dl_num_samples.restype = ctypes.c_long
        lib.dl_num_samples.argtypes = [ctypes.c_void_p]
        lib.dl_next.restype = ctypes.c_long
        lib.dl_next.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.dl_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class NativeCifar10Loader:
    """Infinite iterator of ``(x [B,32,32,3] float32, y [B] int32)`` batches,
    prefetched and shuffled per epoch in C++ worker threads.

    Deterministic for a given ``seed`` (per-epoch Fisher-Yates in the C++
    side); ``epoch`` property reports the epoch of the last batch yielded.

    ``normalize=False`` yields raw uint8 NHWC pixels instead of normalized
    float32 — 4x less host->device traffic; normalize on-device with
    :func:`normalize_on_device` (which XLA fuses into the train step).
    """

    def __init__(
        self,
        data_dir: str | Path,
        batch_size: int,
        seed: int = 0,
        prefetch_depth: int = 4,
        workers: int = 2,
        normalize: bool = True,
    ):
        lib = _load_lib()
        self._lib = lib
        self.normalize = normalize
        self._handle = lib.dl_create(
            str(data_dir).encode(), batch_size, seed, prefetch_depth, workers,
            int(normalize),
        )
        err = lib.dl_error(self._handle)
        if err:
            msg = err.decode()
            lib.dl_destroy(self._handle)
            self._handle = None
            raise NativeLoaderUnavailable(msg)
        self.batch_size = batch_size
        self.num_samples = lib.dl_num_samples(self._handle)
        self.epoch = 0

    def __iter__(self):
        dtype = np.float32 if self.normalize else np.uint8
        x = np.empty((self.batch_size, 32, 32, 3), dtype)
        y = np.empty((self.batch_size,), np.int32)
        xp = x.ctypes.data_as(ctypes.c_void_p)
        yp = y.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        while True:
            epoch = self._lib.dl_next(self._handle, xp, yp)
            if epoch < 0:
                return
            self.epoch = int(epoch)
            yield x.copy(), y.copy()

    def close(self):
        if self._handle is not None:
            self._lib.dl_destroy(self._handle)
            self._handle = None

    def __del__(self):
        # no contextlib.suppress here: at interpreter teardown the module
        # globals may already be cleared, and a finalizer must not do
        # global lookups before reaching the native free
        try:  # noqa: SIM105
            self.close()
        except Exception:
            pass


def normalize_on_device(x_uint8, dtype=None):
    """Device-side CIFAR-10 normalization of raw uint8 NHWC batches (pairs
    with ``NativeCifar10Loader(normalize=False)``); inside jit XLA fuses it
    into the consuming step."""
    import jax.numpy as jnp

    from ddl25spring_tpu.data.cifar10 import MEAN, STD

    x = x_uint8.astype(dtype or jnp.float32)
    mean = jnp.asarray(MEAN, x.dtype) * 255.0
    inv = 1.0 / (jnp.asarray(STD, x.dtype) * 255.0)
    return (x - mean) * inv
