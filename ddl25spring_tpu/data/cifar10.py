"""CIFAR-10 pipeline (benchmark dataset per BASELINE.json).

Zero-egress build: loads the real binary batches when present
(``DDL25_CIFAR10_DIR`` env var or ``data/cifar-10-batches-bin``), else a
deterministic synthetic 32x32x3 class-prototype dataset with identical
shapes/dtypes (throughput benchmarking is shape-bound, not content-bound).
Arrays are NHWC float32, normalized per-channel with the canonical CIFAR-10
train statistics.
"""

from __future__ import annotations

import os
from functools import lru_cache
from pathlib import Path

import numpy as np

MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
STD = np.array([0.2470, 0.2435, 0.2616], np.float32)


def _find_dir() -> Path | None:
    for cand in (
        os.environ.get("DDL25_CIFAR10_DIR"),
        "data/cifar-10-batches-bin",
        "data/cifar10",
    ):
        if (
            cand
            and Path(cand).exists()
            and (Path(cand) / "data_batch_1.bin").exists()
            and (Path(cand) / "test_batch.bin").exists()
        ):
            return Path(cand)
    return None


def _read_bin(path: Path) -> tuple[np.ndarray, np.ndarray]:
    raw = np.frombuffer(path.read_bytes(), dtype=np.uint8).reshape(-1, 3073)
    labels = raw[:, 0].astype(np.int32)
    imgs = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return imgs.astype(np.float32) / 255.0, labels


def _synthetic(n: int, seed: int, noise: float = 0.2):
    proto_rng = np.random.default_rng(4242)
    coarse = proto_rng.random((10, 8, 8, 3)).astype(np.float32)
    protos = np.kron(coarse, np.ones((4, 4, 1), np.float32))  # [10, 32, 32, 3]
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    scale = rng.uniform(0.7, 1.0, size=(n, 1, 1, 1)).astype(np.float32)
    imgs = protos[labels] * scale + rng.normal(0, noise, (n, 32, 32, 3)).astype(
        np.float32
    )
    return np.clip(imgs, 0.0, 1.0), labels


@lru_cache(maxsize=1)
def load_cifar10(n_train: int = 50_000, n_test: int = 10_000, seed: int = 0):
    d = _find_dir()
    if d is not None:
        train_parts = sorted(d.glob("data_batch_*.bin"))
        xs, ys = zip(*(_read_bin(p) for p in train_parts))
        x_tr, y_tr = np.concatenate(xs), np.concatenate(ys)
        x_te, y_te = _read_bin(d / "test_batch.bin")
    else:
        x_tr, y_tr = _synthetic(n_train, seed)
        x_te, y_te = _synthetic(n_test, seed + 1)

    def norm(x):
        return ((x - MEAN) / STD).astype(np.float32)

    return {
        "x_train": norm(x_tr[:n_train]),
        "y_train": y_tr[:n_train],
        "x_test": norm(x_te[:n_test]),
        "y_test": y_te[:n_test],
    }
