"""CIFAR-10 pipeline (benchmark dataset per BASELINE.json).

Zero-egress build: loads the real binary batches when present
(``DDL25_CIFAR10_DIR`` env var or ``data/cifar-10-batches-bin``), else a
deterministic synthetic 32x32x3 class-prototype dataset with identical
shapes/dtypes (throughput benchmarking is shape-bound, not content-bound).
Arrays are NHWC float32, normalized per-channel with the canonical CIFAR-10
train statistics.
"""

from __future__ import annotations

import os
from functools import lru_cache
from pathlib import Path

import numpy as np

MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
STD = np.array([0.2470, 0.2435, 0.2616], np.float32)


def _find_candidate(*marker_groups: tuple[str, ...]) -> Path | None:
    """First candidate dir (env var, then standard paths) satisfying any
    marker group (a group matches when ALL its files exist)."""
    for cand in (
        os.environ.get("DDL25_CIFAR10_DIR"),
        "data/cifar-10-batches-bin",
        "data/cifar10",
    ):
        if cand and Path(cand).exists() and any(
            all((Path(cand) / m).exists() for m in group)
            for group in marker_groups
        ):
            return Path(cand)
    return None


def _find_dir() -> Path | None:
    """Directory with the full canonical layout (train batches + test split)
    — what :func:`load_cifar10` needs."""
    return _find_candidate(("data_batch_1.bin", "test_batch.bin"))


def _find_loader_dir() -> Path | None:
    """Directory usable by the native streaming loader — unlike
    :func:`_find_dir` this accepts the single-file ``train.bin`` layout and
    does not require a test split (``native/dataloader.cc`` supports both)."""
    return _find_candidate(("data_batch_1.bin",), ("train.bin",))


def _read_bin_u8(path: Path) -> tuple[np.ndarray, np.ndarray]:
    raw = np.frombuffer(path.read_bytes(), dtype=np.uint8).reshape(-1, 3073)
    labels = raw[:, 0].astype(np.int32)
    imgs = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return np.ascontiguousarray(imgs), labels


def _read_bin(path: Path) -> tuple[np.ndarray, np.ndarray]:
    imgs, labels = _read_bin_u8(path)
    return imgs.astype(np.float32) / 255.0, labels


def _synthetic(n: int, seed: int, noise: float = 0.2):
    proto_rng = np.random.default_rng(4242)
    coarse = proto_rng.random((10, 8, 8, 3)).astype(np.float32)
    protos = np.kron(coarse, np.ones((4, 4, 1), np.float32))  # [10, 32, 32, 3]
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    scale = rng.uniform(0.7, 1.0, size=(n, 1, 1, 1)).astype(np.float32)
    imgs = protos[labels] * scale + rng.normal(0, noise, (n, 32, 32, 3)).astype(
        np.float32
    )
    return np.clip(imgs, 0.0, 1.0), labels


def ensure_bin_dir(
    n_records: int = 50_000, seed: int = 0, synth_dir: str = "data/cifar10-synth-bin"
) -> tuple[Path, str]:
    """Directory of CIFAR-10 binary batches for the native streaming loader.

    Returns ``(dir, provenance)`` where provenance is ``"real"`` when the
    canonical binaries are present (``DDL25_CIFAR10_DIR`` / data dirs) and
    ``"synthetic"`` otherwise — in which case a CIFAR-format ``train.bin``
    is written once (uint8 quantization of :func:`_synthetic`) so the C++
    prefetcher exercises its real parse/shuffle/assemble path and benchmarks
    measure true input-pipeline cost even on a zero-egress image.
    """
    d = _find_loader_dir()
    if d is not None:
        return d, "real"
    out = Path(synth_dir)
    f = out / "train.bin"
    want_bytes = n_records * 3073
    if not (f.exists() and f.stat().st_size == want_bytes):
        out.mkdir(parents=True, exist_ok=True)
        imgs, labels = _synthetic(n_records, seed)
        chw = np.round(imgs.transpose(0, 3, 1, 2) * 255.0).astype(np.uint8)
        rec = np.empty((n_records, 3073), np.uint8)
        rec[:, 0] = labels.astype(np.uint8)
        rec[:, 1:] = chw.reshape(n_records, -1)
        tmp = f.with_suffix(".bin.tmp")
        tmp.write_bytes(rec.tobytes())
        tmp.replace(f)
    return out, "synthetic"


@lru_cache(maxsize=1)
def load_cifar10_u8(n_train: int = 50_000, seed: int = 0):
    """Raw uint8 NHWC training images + int32 labels (real binaries when
    present, quantized synthetic otherwise) — the device-side-normalization
    input format (pair with ``native_loader.normalize_on_device``).  Always
    returns exactly ``n_train`` rows (short real datasets are tiled)."""
    d = _find_loader_dir()
    if d is not None:
        parts = sorted(d.glob("data_batch_*.bin")) or [d / "train.bin"]
        xs, ys = zip(*(_read_bin_u8(p) for p in parts))
        x, y = np.concatenate(xs), np.concatenate(ys)
        provenance = "real"
        if len(x) < n_train:
            reps = -(-n_train // len(x))
            x = np.tile(x, (reps, 1, 1, 1))
            y = np.tile(y, reps)
    else:
        x01, y = _synthetic(n_train, seed)
        x = np.round(x01 * 255.0).astype(np.uint8)
        provenance = "synthetic"
    return {"x": x[:n_train], "y": y[:n_train], "provenance": provenance}


@lru_cache(maxsize=1)
def load_cifar10(n_train: int = 50_000, n_test: int = 10_000, seed: int = 0):
    d = _find_dir()
    if d is not None:
        train_parts = sorted(d.glob("data_batch_*.bin"))
        xs, ys = zip(*(_read_bin(p) for p in train_parts))
        x_tr, y_tr = np.concatenate(xs), np.concatenate(ys)
        x_te, y_te = _read_bin(d / "test_batch.bin")
    else:
        x_tr, y_tr = _synthetic(n_train, seed)
        x_te, y_te = _synthetic(n_test, seed + 1)

    def norm(x):
        return ((x - MEAN) / STD).astype(np.float32)

    return {
        "x_train": norm(x_tr[:n_train]),
        "y_train": y_tr[:n_train],
        "x_test": norm(x_te[:n_test]),
        "y_test": y_te[:n_test],
    }
