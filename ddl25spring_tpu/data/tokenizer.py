"""Tokenizers.

The reference tokenizes through simplellm's ``SPTokenizer`` (SentencePiece,
C++ — ``lab/s01_b1_microbatches.py:6,31``), whose artifacts are gitignored
(``lab/tutorial_1b/.gitignore:8,28``) and fetched at first run.  Tokenization
never runs on TPU (SURVEY §2), so the in-tree default is a dependency-free
byte-level tokenizer with the same API surface (``vocab_size``, ``pad_id``,
``encode``/``decode``); a SentencePiece wrapper is provided when the package
is importable.

Because this image ships NEITHER the sentencepiece package nor a model
artifact, the trained-subword capability (the thing SPTokenizer actually
adds over bytes) is covered by :class:`BpeTokenizer` — a dependency-free
byte-level BPE that is TRAINED on a corpus, serialized to a JSON artifact,
and auto-discovered by :func:`get_tokenizer` exactly like an SP model file
would be (``DDL25_SP_MODEL`` / ``DDL25_BPE_MODEL`` env vars, then
``data/*.model`` / ``data/bpe.json``).  Exercised end-to-end (train ->
save -> load -> encode -> LLaMA train step) in ``tests/test_text_data.py``.
"""

from __future__ import annotations

import ctypes
import json
import os
import re
import threading
from pathlib import Path

import numpy as np

# ---------------------------------------------------------------- native BPE
# The reference's tokenizer is native C++ (SentencePiece inside simplellm);
# the in-tree equivalent keeps the hot encode loop native too: native/bpe.cc
# implements the exact greedy merge scan, built on demand like the C++
# dataloader.  Failure to build/load falls back to the Python loop silently
# (same contract, just slower).
_BPE_LIB_NAME = "libddl25_bpe.so"
_bpe_lib_lock = threading.Lock()
_bpe_lib: ctypes.CDLL | bool | None = None  # None=untried, False=unavailable


def _load_bpe_lib():
    global _bpe_lib
    with _bpe_lib_lock:
        if _bpe_lib is not None:
            return _bpe_lib or None
        from ddl25spring_tpu.data.native_loader import (
            NativeLoaderUnavailable, load_native_lib,
        )

        try:
            lib = load_native_lib(_BPE_LIB_NAME)
        except NativeLoaderUnavailable:
            _bpe_lib = False
            return None
        lib.bpe_create.restype = ctypes.c_void_p
        lib.bpe_create.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
        ]
        lib.bpe_destroy.argtypes = [ctypes.c_void_p]
        lib.bpe_encode.restype = ctypes.c_long
        lib.bpe_encode.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32),
        ]
        _bpe_lib = lib
        return lib


class ByteTokenizer:
    """Byte-level tokenizer: ids = byte value + 3; 0/1/2 = pad/bos/eos."""

    pad_id = 0
    bos_id = 1
    eos_id = 2
    vocab_size = 256 + 3

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = [b + 3 for b in text.encode("utf-8")]
        return ([self.bos_id] if add_bos else []) + ids

    def decode(self, ids) -> str:
        return bytes(i - 3 for i in np.asarray(ids).tolist() if i >= 3).decode(
            "utf-8", errors="replace"
        )


class SentencePieceTokenizer:
    """Wrapper matching simplellm's ``SPTokenizer`` surface.

    Uses the sentencepiece package (host-side C++) when importable;
    otherwise the in-tree pure-Python processor
    (:class:`~ddl25spring_tpu.data.sp_model.PySentencePieceProcessor`),
    which reads the SAME ``.model`` protobuf format and encodes by
    unigram Viterbi — so real SentencePiece artifacts work on images
    without the package (this one), and the in-tree-trained artifact
    works under real SentencePiece."""

    def __init__(self, model_path: str):
        try:
            import sentencepiece as spm  # gated import

            self._sp = spm.SentencePieceProcessor(model_file=model_path)
        except ImportError:
            import warnings

            from ddl25spring_tpu.data.sp_model import (
                PySentencePieceProcessor,
            )

            # one-time (warnings dedup per call site): the pure-Python
            # processor is an APPROXIMATION of real SentencePiece — see
            # the divergence notes in ddl25spring_tpu/data/sp_model.py's
            # module docstring (no NFKC normalization, no byte-fallback
            # pieces) — so a silent swap could mask tokenization drift
            warnings.warn(
                "sentencepiece is not importable; falling back to the "
                "in-tree PySentencePieceProcessor for "
                f"{model_path!r}. Encodings approximate real "
                "SentencePiece (unigram Viterbi without NFKC "
                "normalization or byte-fallback; see "
                "ddl25spring_tpu/data/sp_model.py).",
                stacklevel=2,
            )
            self._sp = PySentencePieceProcessor(model_path)
        self.vocab_size = self._sp.vocab_size()
        # keep SentencePiece's -1 sentinel when the model has no pad piece:
        # coercing to 0 would alias <unk> and silently mask it out of losses
        self.pad_id = self._sp.pad_id()
        self.bos_id = self._sp.bos_id()
        self.eos_id = self._sp.eos_id()

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = self._sp.encode(text)
        return ([self.bos_id] if add_bos and self.bos_id >= 0 else []) + ids

    def decode(self, ids) -> str:
        return self._sp.decode(np.asarray(ids).tolist())


class BpeTokenizer:
    """Byte-level BPE, trainable and serializable, zero dependencies.

    The in-tree replacement for the trained-subword capability of the
    reference's SentencePiece path (``lab/s01_b1_microbatches.py:6,31``):
    merges are LEARNED from a corpus (greedy most-frequent-pair, the
    standard BPE recipe), stored as a JSON artifact, and reloaded by id.
    Id space: 0/1/2 = pad/bos/eos, 3..258 = bytes, 259+i = merge i.

    Round-trip exactness: text is chunked by ``\\s*\\S+`` (whitespace
    travels with the following word), merges never cross chunk bounds,
    and decode is plain byte expansion — so ``decode(encode(t)) == t``
    for any text.
    """

    pad_id = 0
    bos_id = 1
    eos_id = 2
    _BYTE0 = 3  # id of byte 0

    def __init__(self, merges: list[tuple[int, int]], native: bool = True):
        self.merges = [tuple(m) for m in merges]
        self._rank = {m: i for i, m in enumerate(self.merges)}
        self.vocab_size = 256 + self._BYTE0 + len(self.merges)
        # id -> bytes expansion table
        self._bytes: dict[int, bytes] = {
            self._BYTE0 + b: bytes([b]) for b in range(256)
        }
        for i, (a, b) in enumerate(self.merges):
            self._bytes[259 + i] = self._bytes[a] + self._bytes[b]
        # native C++ encode loop (native/bpe.cc) when buildable; the
        # Python path below is the reference implementation and fallback
        self._native = None
        lib = _load_bpe_lib() if native else None
        if lib is not None:
            flat = np.asarray(self.merges, np.int32).reshape(-1)
            handle = lib.bpe_create(
                flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                len(self.merges),
            )
            if handle:
                self._native = (lib, ctypes.c_void_p(handle))

    def __del__(self):
        native = getattr(self, "_native", None)
        if native is not None:
            lib, handle = native
            # bare try/except, not contextlib.suppress: at interpreter
            # teardown module globals may be cleared and a finalizer
            # must not do global lookups before the native free
            try:  # noqa: SIM105
                lib.bpe_destroy(handle)
            except Exception:
                pass


    # ------------------------------------------------------------ training
    @classmethod
    def train(cls, corpus: str, n_merges: int = 512) -> "BpeTokenizer":
        """Greedy BPE: repeatedly merge the most frequent adjacent id pair
        over the chunked corpus (counts weighted by chunk frequency)."""
        words: dict[tuple[int, ...], int] = {}
        for chunk in re.findall(r"\s*\S+", corpus):
            ids = tuple(cls._BYTE0 + b for b in chunk.encode("utf-8"))
            words[ids] = words.get(ids, 0) + 1
        merges: list[tuple[int, int]] = []
        for _ in range(n_merges):
            pairs: dict[tuple[int, int], int] = {}
            for ids, cnt in words.items():
                for pair in zip(ids, ids[1:]):
                    pairs[pair] = pairs.get(pair, 0) + cnt
            if not pairs:
                break
            best = max(pairs, key=pairs.get)
            if pairs[best] < 2:
                break
            new_id = 259 + len(merges)
            merges.append(best)
            words = {
                cls._apply_one(ids, best, new_id): cnt
                for ids, cnt in words.items()
            }
        return cls(merges)

    @staticmethod
    def _apply_one(ids, pair, new_id):
        out, i = [], 0
        while i < len(ids):
            if i + 1 < len(ids) and (ids[i], ids[i + 1]) == pair:
                out.append(new_id)
                i += 2
            else:
                out.append(ids[i])
                i += 1
        return tuple(out)

    # ---------------------------------------------------------- save/load
    def save(self, path: str) -> None:
        Path(path).write_text(
            json.dumps({"format": "ddl25-bpe-v1", "merges": self.merges})
        )

    @classmethod
    def load(cls, path: str) -> "BpeTokenizer":
        obj = json.loads(Path(path).read_text())
        if obj.get("format") != "ddl25-bpe-v1":
            raise ValueError(f"{path}: not a ddl25-bpe-v1 artifact")
        return cls([tuple(m) for m in obj["merges"]])

    # ------------------------------------------------------- encode/decode
    def _encode_chunk(self, chunk: bytes) -> list[int]:
        ids = [self._BYTE0 + b for b in chunk]
        while len(ids) > 1:
            ranked = [
                (self._rank.get(p, len(self.merges)), j)
                for j, p in enumerate(zip(ids, ids[1:]))
            ]
            r, j = min(ranked)
            if r == len(self.merges):
                break
            ids[j : j + 2] = [259 + r]
        return ids

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        if self._native is not None:
            lib, handle = self._native
            data = text.encode("utf-8")
            out = np.empty(len(data) + 1, np.int32)  # ids never outnumber bytes
            n = lib.bpe_encode(
                handle, data, len(data), int(add_bos),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
            return out[:n].tolist()
        ids = [self.bos_id] if add_bos else []
        for chunk in re.findall(r"\s*\S+|\s+$", text):
            ids.extend(self._encode_chunk(chunk.encode("utf-8")))
        return ids

    def decode(self, ids) -> str:
        out = b"".join(
            self._bytes[i]
            for i in np.asarray(ids).tolist()
            if i >= self._BYTE0
        )
        return out.decode("utf-8", errors="replace")


def get_tokenizer(model_path: str | None = None):
    """Tokenizer resolution, mirroring the reference's artifact discovery
    (SPTokenizer loads a fetched model file): an explicit path wins; then
    env-var/conventional-path artifacts (real SentencePiece model, then
    the in-tree BPE artifact); else the byte tokenizer."""
    if model_path is not None:
        if model_path.endswith(".json"):
            return BpeTokenizer.load(model_path)
        return SentencePieceTokenizer(model_path)
    sp = os.environ.get("DDL25_SP_MODEL")
    if sp and Path(sp).exists():
        return SentencePieceTokenizer(sp)
    bpe = os.environ.get("DDL25_BPE_MODEL", "data/bpe.json")
    if bpe and Path(bpe).exists():
        return BpeTokenizer.load(bpe)
    return ByteTokenizer()
