"""Tokenizers.

The reference tokenizes through simplellm's ``SPTokenizer`` (SentencePiece,
C++ — ``lab/s01_b1_microbatches.py:6,31``), whose artifacts are gitignored
(``lab/tutorial_1b/.gitignore:8,28``) and fetched at first run.  Tokenization
never runs on TPU (SURVEY §2), so the in-tree default is a dependency-free
byte-level tokenizer with the same API surface (``vocab_size``, ``pad_id``,
``encode``/``decode``); a SentencePiece wrapper is provided when the package
is importable.
"""

from __future__ import annotations

import numpy as np


class ByteTokenizer:
    """Byte-level tokenizer: ids = byte value + 3; 0/1/2 = pad/bos/eos."""

    pad_id = 0
    bos_id = 1
    eos_id = 2
    vocab_size = 256 + 3

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = [b + 3 for b in text.encode("utf-8")]
        return ([self.bos_id] if add_bos else []) + ids

    def decode(self, ids) -> str:
        return bytes(i - 3 for i in np.asarray(ids).tolist() if i >= 3).decode(
            "utf-8", errors="replace"
        )


class SentencePieceTokenizer:
    """Wrapper matching simplellm's ``SPTokenizer`` surface, gated on the
    sentencepiece package being available (it is host-side C++, off the TPU
    hot path)."""

    def __init__(self, model_path: str):
        import sentencepiece as spm  # gated import

        self._sp = spm.SentencePieceProcessor(model_file=model_path)
        self.vocab_size = self._sp.vocab_size()
        # keep SentencePiece's -1 sentinel when the model has no pad piece:
        # coercing to 0 would alias <unk> and silently mask it out of losses
        self.pad_id = self._sp.pad_id()
        self.bos_id = self._sp.bos_id()
        self.eos_id = self._sp.eos_id()

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = self._sp.encode(text)
        return ([self.bos_id] if add_bos and self.bos_id >= 0 else []) + ids

    def decode(self, ids) -> str:
        return self._sp.decode(np.asarray(ids).tolist())


def get_tokenizer(model_path: str | None = None):
    if model_path is not None:
        return SentencePieceTokenizer(model_path)
    return ByteTokenizer()
