"""SentencePiece ``.model`` files without the sentencepiece package.

The reference tokenizes TinyStories through simplellm's ``SPTokenizer`` —
a SentencePiece model loaded from a gitignored ``*.model`` artifact
(``lab/s01_b1_microbatches.py:6,31``, ``lab/tutorial_1b/.gitignore:8,28``).
The sentencepiece package is host-side C++ and is NOT part of this image,
which previously left the wrapper in :mod:`ddl25spring_tpu.data.tokenizer`
dead code.  This module makes the format first-class with zero
dependencies:

- :func:`read_sp_model` / :func:`write_sp_model` — the ``ModelProto``
  protobuf wire format, hand-decoded/encoded (the format is stable and
  tiny: ``repeated SentencePiece {piece: string = 1, score: float = 2,
  type: enum = 3} pieces = 1``; every other field is skipped on read and
  omitted on write, which the protobuf wire format makes legal).  A REAL
  SentencePiece ``.model`` therefore loads here, and a model written here
  loads in real SentencePiece.
- :class:`PySentencePieceProcessor` — the inference surface the wrapper
  needs (``vocab_size``/``pad_id``/``bos_id``/``eos_id``/``encode``/
  ``decode``), encoding by unigram Viterbi: SentencePiece's default
  algorithm — maximize the sum of piece log-probs over a segmentation,
  after the standard normalization (spaces to ``▁`` with a dummy
  prefix).  Characters no piece covers fall back to ``<unk>`` with a
  large penalty, exactly the unigram model's unknown handling.
- :func:`train_sp_model` — a frequency-based unigram trainer: candidate
  pieces are frequent substrings of the normalized words (plus all
  single characters for closure), scored by ``log`` relative frequency.
  This is the seed-vocabulary stage of the real unigram trainer without
  the EM prune loop — an honest simplification that yields a valid,
  functional model file; swap in a real SentencePiece-trained artifact
  any time and everything downstream is unchanged.

Known divergences from real SentencePiece (the wrapper in
:mod:`ddl25spring_tpu.data.tokenizer` warns once when it swaps this in):

- **Normalizer**: real SentencePiece applies the model's precompiled
  normalizer before segmentation — by default ``nmt_nfkc`` (NFKC
  Unicode normalization plus space folding).  This module only performs
  the space -> ``▁`` replacement with a dummy prefix and skips NFKC
  entirely (the precompiled charsmap in the proto is not decoded), so
  text containing compatibility characters (full-width forms, ligatures
  like ``ﬁ``, superscripts) segments differently than under the real
  library.
- **Byte fallback**: models trained with ``--byte_fallback`` carry 256
  ``<0xNN>`` BYTE-type pieces so any character not covered by the vocab
  still encodes losslessly.  Here uncovered characters map to ``<unk>``
  with a large Viterbi penalty instead — decode cannot round-trip them,
  exactly the lossy behavior byte fallback exists to avoid (the in-tree
  :class:`~ddl25spring_tpu.data.tokenizer.BpeTokenizer` is the
  dependency-free choice when round-trip exactness matters).

TPU note: tokenization is host-side and off the hot path (the reference's
is too); this module exists for capability parity + artifact
compatibility, not speed.
"""

from __future__ import annotations

import math
import struct
from collections import Counter
from pathlib import Path

_WS = "▁"  # SentencePiece's meta symbol for space

# SentencePiece piece types (sentencepiece_model.proto enum)
NORMAL = 1
UNKNOWN = 2
CONTROL = 3
BYTE = 6


# ------------------------------------------------------------ wire format


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, i: int) -> tuple[int, int]:
    n = shift = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def _skip_field(buf: bytes, i: int, wire: int) -> int:
    if wire == 0:  # varint
        _, i = _read_varint(buf, i)
    elif wire == 1:  # 64-bit
        i += 8
    elif wire == 2:  # length-delimited
        ln, i = _read_varint(buf, i)
        i += ln
    elif wire == 5:  # 32-bit
        i += 4
    else:
        raise ValueError(f"unsupported protobuf wire type {wire}")
    return i


def write_sp_model(
    pieces: list[tuple[str, float, int]], path: str | Path
) -> None:
    """Serialize ``(piece, score, type)`` triples as a ``ModelProto``."""
    out = bytearray()
    for piece, score, ptype in pieces:
        sub = bytearray()
        pb = piece.encode("utf-8")
        sub += b"\x0a" + _varint(len(pb)) + pb          # piece = 1, wire 2
        sub += b"\x15" + struct.pack("<f", score)        # score = 2, wire 5
        sub += b"\x18" + _varint(ptype)                  # type  = 3, wire 0
        out += b"\x0a" + _varint(len(sub)) + sub         # pieces = 1, wire 2
    Path(path).write_bytes(bytes(out))


def read_sp_model(path: str | Path) -> list[tuple[str, float, int]]:
    """Parse a ``ModelProto`` into ``(piece, score, type)`` triples —
    real SentencePiece artifacts included (unknown fields skipped)."""
    buf = Path(path).read_bytes()
    pieces: list[tuple[str, float, int]] = []
    i = 0
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == 2:  # repeated SentencePiece
            ln, i = _read_varint(buf, i)
            sub, j = buf[i : i + ln], 0
            i += ln
            piece, score, ptype = "", 0.0, NORMAL
            while j < len(sub):
                t, j = _read_varint(sub, j)
                f, w = t >> 3, t & 7
                if f == 1 and w == 2:
                    sln, j = _read_varint(sub, j)
                    piece = sub[j : j + sln].decode("utf-8")
                    j += sln
                elif f == 2 and w == 5:
                    (score,) = struct.unpack("<f", sub[j : j + 4])
                    j += 4
                elif f == 3 and w == 0:
                    ptype, j = _read_varint(sub, j)
                else:
                    j = _skip_field(sub, j, w)
            pieces.append((piece, score, ptype))
        else:
            i = _skip_field(buf, i, wire)
    return pieces


# ------------------------------------------------------------ inference


def _normalize(text: str) -> str:
    # the standard SentencePiece front end: collapse spaces to the meta
    # symbol with a dummy prefix so word starts are marked
    return _WS + text.replace(" ", _WS)


class PySentencePieceProcessor:
    """Pure-Python stand-in for ``sentencepiece.SentencePieceProcessor``
    (the load/encode/decode slice the tokenizer wrapper uses)."""

    def __init__(self, model_file: str | Path):
        self.pieces = read_sp_model(model_file)
        if not self.pieces:
            raise ValueError(f"{model_file}: no pieces parsed")
        self._id = {p: i for i, (p, _, _) in enumerate(self.pieces)}
        self._unk = next(
            (i for i, (_, _, t) in enumerate(self.pieces) if t == UNKNOWN), 0
        )
        self._max_len = max(len(p) for p, _, _ in self.pieces)

        def ctl(name: str) -> int:
            return self._id.get(name, -1)

        self._bos = ctl("<s>")
        self._eos = ctl("</s>")
        self._pad = ctl("<pad>")

    # -- the SPTokenizer-visible surface ---------------------------------
    def vocab_size(self) -> int:
        return len(self.pieces)

    def pad_id(self) -> int:
        return self._pad

    def bos_id(self) -> int:
        return self._bos

    def eos_id(self) -> int:
        return self._eos

    def encode(self, text: str) -> list[int]:
        """Unigram Viterbi: the segmentation maximizing the summed piece
        scores; uncovered characters emit ``<unk>`` at a large penalty."""
        s = _normalize(text)
        n = len(s)
        NEG = -1e18
        best = [NEG] * (n + 1)
        back: list[tuple[int, int]] = [(-1, -1)] * (n + 1)  # (prev, id)
        best[0] = 0.0
        unk_penalty = -100.0
        for i in range(1, n + 1):
            lo = max(0, i - self._max_len)
            for j in range(lo, i):
                if best[j] == NEG:
                    continue
                pid = self._id.get(s[j:i])
                if pid is None:
                    continue
                sc = best[j] + self.pieces[pid][1]
                if sc > best[i]:
                    best[i] = sc
                    back[i] = (j, pid)
            if best[i] == NEG and best[i - 1] != NEG:
                # unknown character: single-char <unk> step
                best[i] = best[i - 1] + unk_penalty
                back[i] = (i - 1, self._unk)
        ids: list[int] = []
        i = n
        while i > 0:
            j, pid = back[i]
            ids.append(pid)
            i = j
        return ids[::-1]

    def decode(self, ids) -> str:
        # real SentencePiece skips CONTROL pieces but renders UNKNOWN as
        # " ⁇ " — silent dropping would lose characters on out-of-vocab
        # input, breaking parity exactly where it matters
        parts = []
        for i in ids:
            i = int(i)
            if not 0 <= i < len(self.pieces):
                continue
            piece, _, ptype = self.pieces[i]
            if ptype == CONTROL:
                continue
            parts.append(" ⁇ " if ptype == UNKNOWN else piece)
        return "".join(parts).replace(_WS, " ").lstrip(" ")


# ------------------------------------------------------------ training


def train_sp_model(
    texts,
    vocab_size: int,
    path: str | Path,
    max_piece_len: int = 8,
) -> None:
    """Train a unigram-style model and write it as a ``.model`` file.

    Seed-vocabulary recipe (the first stage of SentencePiece's unigram
    trainer): count all substrings of the normalized words up to
    ``max_piece_len``, keep the most frequent until ``vocab_size`` is
    filled (all single characters always kept so every input is
    coverable), score = log relative frequency.  Control pieces
    ``<pad>/<s>/</s>/<unk>`` take ids 0-3 like standard artifacts."""
    words = Counter()
    for t in texts:
        for w in t.split(" "):
            if w:
                words[_WS + w] += 1

    subs: Counter = Counter()
    chars: Counter = Counter()
    for w, c in words.items():
        for i in range(len(w)):
            chars[w[i]] += c
            for ln in range(2, max_piece_len + 1):
                if i + ln <= len(w):
                    subs[w[i : i + ln]] += c * ln  # favor longer pieces

    control = [("<pad>", 0.0, CONTROL), ("<s>", 0.0, CONTROL),
               ("</s>", 0.0, CONTROL), ("<unk>", 0.0, UNKNOWN)]
    budget = vocab_size - len(control) - len(chars)
    if budget < 0:
        raise ValueError(
            f"vocab_size={vocab_size} cannot even hold the "
            f"{len(chars)} single characters"
        )
    chosen = [p for p, _ in subs.most_common(budget)]
    total = sum(chars.values()) + sum(subs[p] for p in chosen) or 1

    def score(freq: int) -> float:
        return math.log(max(freq, 1) / total)

    pieces = control + sorted(
        [(p, score(chars[p]), NORMAL) for p in chars]
        + [(p, score(subs[p]), NORMAL) for p in chosen],
        key=lambda x: -x[1],
    )
    write_sp_model(pieces, path)
