"""Federated client data splitter.

Same capability as the reference's ``split(nr_clients, iid, seed)``
(``lab/tutorial_1a/hfl_complete.py:91-104``):

- IID: permute all indices, ``array_split`` into ``nr_clients`` chunks;
- non-IID: sort by label, cut into ``2 * nr_clients`` shards, deal each
  client 2 randomly-chosen shards (so each client sees at most ~2 labels).

Returns index arrays (not dataset objects) so callers can build stacked,
padded per-client arrays for the vmapped federated layer.
"""

from __future__ import annotations

import numpy as np


def split_indices(
    labels: np.ndarray, nr_clients: int, iid: bool, seed: int
) -> list[np.ndarray]:
    rng = np.random.default_rng(seed)
    n = len(labels)
    if iid:
        return [s.astype(np.int64) for s in np.array_split(rng.permutation(n), nr_clients)]
    sorted_indices = np.argsort(labels, kind="stable")
    shards = np.array_split(sorted_indices, 2 * nr_clients)
    order = rng.permutation(len(shards)).reshape(nr_clients, 2)
    return [
        np.concatenate([shards[i] for i in pair]).astype(np.int64) for pair in order
    ]


def stack_client_data(
    x: np.ndarray, y: np.ndarray, splits: list[np.ndarray]
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Build dense ``[n_clients, max_n, ...]`` arrays + per-client counts.

    Clients' shards differ in size (non-IID especially); the vmapped client
    axis needs rectangular arrays, so shorter clients are padded by repeating
    their own examples (repeats are masked out of weighted aggregation by the
    true ``counts``, matching the reference's weighting by sample count at
    ``hfl_complete.py:292,371``).
    """
    counts = np.array([len(s) for s in splits], dtype=np.int32)
    if (counts == 0).any():
        raise ValueError(
            f"empty client split (sizes {counts.tolist()}): need at least one "
            "example per client; use fewer clients or more data"
        )
    max_n = int(counts.max())
    xs, ys = [], []
    for s in splits:
        reps = -(-max_n // len(s))  # ceil
        idx = np.tile(s, reps)[:max_n]
        xs.append(x[idx])
        ys.append(y[idx])
    return np.stack(xs), np.stack(ys), counts
