"""Heart-disease tabular data pipeline.

The reference reads ``heart.csv`` (UCI Cleveland layout: 13 columns +
``target``) and preprocesses with one-hot categoricals + scaled numericals
(``lab/tutorial_2b/vfl.py:106-141``, ``lab/tutorial_2a/centralized.py:31-41``).
This loader reproduces that shape contract in numpy:

- one-hot: sex, cp, fbs, restecg, exang, slope, ca, thal;
- numericals scaled (min-max by default, matching the VFL/centralized
  scripts; standardization available for the VAE script's preprocessing);
- the encoded matrix lands at ~30 features, the input width of
  ``HeartDiseaseNN``.

Sources: ``DDL25_HEART_CSV`` env var, ``data/heart.csv``, else a
deterministic synthetic generator with the same schema and a real
label-feature dependence (so classifiers beat chance).
"""

from __future__ import annotations

import csv
import os
from functools import lru_cache
from pathlib import Path

import numpy as np

CATEGORICAL = ["sex", "cp", "fbs", "restecg", "exang", "slope", "ca", "thal"]
NUMERICAL = ["age", "trestbps", "chol", "thalach", "oldpeak"]
COLUMNS = [
    "age", "sex", "cp", "trestbps", "chol", "fbs", "restecg", "thalach",
    "exang", "oldpeak", "slope", "ca", "thal", "target",
]
# category cardinalities in the UCI data (sex 2, cp 4, fbs 2, restecg 3,
# exang 2, slope 3, ca 5, thal 4)
_CARD = {"sex": 2, "cp": 4, "fbs": 2, "restecg": 3, "exang": 2, "slope": 3,
         "ca": 5, "thal": 4}


def _find_csv() -> Path | None:
    for cand in (os.environ.get("DDL25_HEART_CSV"), "data/heart.csv"):
        if cand and Path(cand).exists():
            return Path(cand)
    return None


def _synthetic(n: int, seed: int) -> dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    rows = {
        "age": rng.integers(29, 78, n),
        "trestbps": rng.integers(94, 201, n),
        "chol": rng.integers(126, 565, n),
        "thalach": rng.integers(71, 203, n),
        "oldpeak": np.round(rng.uniform(0, 6.2, n), 1),
    }
    for c, k in _CARD.items():
        rows[c] = rng.integers(0, k, n)
    # target depends on a few features so models can learn
    logit = 3.0 * (
        0.03 * (rows["age"] - 54)
        + 0.8 * (rows["cp"] > 0)
        - 0.015 * (rows["thalach"] - 150)
        + 0.5 * rows["exang"]
        + 0.4 * (rows["oldpeak"] > 1.5)
        - 0.6
    )
    rows["target"] = (1 / (1 + np.exp(-logit)) > rng.uniform(0, 1, n)).astype(int)
    return {k: np.asarray(v) for k, v in rows.items()}


def _read_csv(path: Path) -> dict[str, np.ndarray]:
    with open(path) as f:
        reader = csv.DictReader(f)
        rows = list(reader)
    return {
        c: np.asarray([float(r[c]) for r in rows]) for c in COLUMNS
    }


def _freeze(d: dict) -> dict:
    for v in d.values():
        if isinstance(v, np.ndarray):
            v.flags.writeable = False  # lru_cache shares the dict: no aliasing bugs
    return d


@lru_cache(maxsize=4)
def load_heart(
    n_synthetic: int = 1025, seed: int = 42, scale: str = "minmax"
) -> dict:
    """Return ``{"x": [N,F] float32, "y": [N] int32, "feature_names",
    "feature_slices"}`` where feature_slices maps each ORIGINAL column to its
    (start, stop) range in the encoded matrix — the handle VFL uses to deal
    disjoint feature groups to parties (``vfl.py:116-141``)."""
    p = _find_csv()
    raw = _read_csv(p) if p is not None else _synthetic(n_synthetic, seed)

    cols: list[np.ndarray] = []
    names: list[str] = []
    slices: dict[str, tuple[int, int]] = {}
    for c in COLUMNS[:-1]:
        start = sum(x.shape[1] for x in cols)
        if c in CATEGORICAL:
            vals = raw[c].astype(int)
            k = max(_CARD.get(c, 0), vals.max() + 1)
            onehot = np.zeros((len(vals), k), np.float32)
            onehot[np.arange(len(vals)), vals] = 1.0
            cols.append(onehot)
            names += [f"{c}_{i}" for i in range(k)]
        else:
            v = raw[c].astype(np.float32)
            if scale == "minmax":
                v = (v - v.min()) / max(v.max() - v.min(), 1e-8)
            else:  # standardize (VAE script's choice)
                v = (v - v.mean()) / max(v.std(), 1e-8)
            cols.append(v[:, None])
            names.append(c)
        slices[c] = (start, sum(x.shape[1] for x in cols))

    x = np.concatenate(cols, axis=1).astype(np.float32)
    y = raw["target"].astype(np.int32)
    return _freeze(
        {
            "x": x,
            "y": y,
            "feature_names": names,
            "feature_slices": slices,
            "provenance": "real" if p is not None else "synthetic",
        }
    )


def partition_features(
    feature_slices: dict[str, tuple[int, int]], n_parties: int
) -> list[np.ndarray]:
    """Deal the 13 original columns round the parties the way the reference
    does — floor(13/K) raw columns per party, remainder to the last, each
    expanded to its one-hot columns (``vfl.py:116-141``).  Returns per-party
    encoded-column index arrays (disjoint, covering)."""
    cols = list(feature_slices)
    per = len(cols) // n_parties
    groups = [cols[i * per : (i + 1) * per] for i in range(n_parties - 1)]
    groups.append(cols[(n_parties - 1) * per :])
    out = []
    for g in groups:
        idx: list[int] = []
        for c in g:
            lo, hi = feature_slices[c]
            idx.extend(range(lo, hi))
        out.append(np.asarray(idx, dtype=np.int64))
    return out
